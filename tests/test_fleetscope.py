"""mxtpu.fleetscope — cross-process distributed tracing.

Covers the fleetscope acceptance surface: strict W3C-traceparent
parsing (malformed headers counted and re-minted, never guessed),
the accept() root-vs-mid-trace minting matrix, hand-computed NTP
midpoint offset estimation with its rtt/2 error bound, the
clock-aligned merge (injected skew, mono authority under an NTP step
inside one process), the collector's never-raise discipline against a
dead target, the off-path zero-overhead predicate, the
check_fleetscope_extra good/bad schema matrix, serve_load's
build_fleetscope_extra assembly, and an in-process router → replica
propagation end-to-end (one request = ONE trace across a real HTTP
hop, wire gap a skew-free duration difference).

Everything here is in-process and CPU-only; the spawned-worker
multi-process path is exercised end to end by tools/fleetscope_smoke.sh.
"""
import json
import urllib.request

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fleetscope, gluon, nd, servescope
from incubator_mxnet_tpu.fleet import ReplicaSet, Router
from incubator_mxnet_tpu.fleetscope import (Collector, TraceContext,
                                            estimate_offset, join_traces,
                                            merge_process_events, mint,
                                            parse)
from incubator_mxnet_tpu.healthmon import events as hm_events
from incubator_mxnet_tpu.serving import FrozenModel, ModelServer


def _mlp(in_units=6, out=3, seed=0):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=in_units, activation="relu"),
            gluon.nn.Dense(out, in_units=16))
    net.initialize(init=mx.init.Xavier())
    rng = np.random.RandomState(seed)
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype(np.float32) * 0.1))
    return net


def _factory(compile_cache=None):
    return FrozenModel(_mlp(), input_shape=(6,), batch_buckets=(1, 2, 4),
                       compile_cache=compile_cache)


@pytest.fixture
def frozen():
    return _factory()


@pytest.fixture
def armed():
    """Fleetscope + servescope armed (and always disarmed after)."""
    servescope.enable()
    fs = fleetscope.enable()
    yield fs
    fleetscope.disable()
    servescope.disable()


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, f"tools/{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _post(url, doc, headers=None, timeout=30):
    body = json.dumps(doc).encode()
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(url, data=body, headers=h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ---------------------------------------------------------------------------
# context: mint / parse / child
# ---------------------------------------------------------------------------

def test_mint_parse_roundtrip():
    ctx = mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = parse(ctx.header())
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled is True


def test_parse_is_strict():
    good = f"00-{'a' * 32}-{'b' * 16}-01"
    assert parse(good) is not None
    # whitespace + case are normalized, per the lenient-read half of
    # the robustness principle
    assert parse(f"  {good.upper()}  ") is not None
    for bad in (None, 42, "", "garbage",
                f"01-{'a' * 32}-{'b' * 16}-01",      # unknown version
                f"00-{'a' * 31}-{'b' * 16}-01",      # short trace
                f"00-{'a' * 32}-{'b' * 15}-01",      # short span
                f"00-{'g' * 32}-{'b' * 16}-01",      # non-hex
                f"00-{'0' * 32}-{'b' * 16}-01",      # zero trace
                f"00-{'a' * 32}-{'0' * 16}-01"):     # zero span
        assert parse(bad) is None, bad


def test_parse_sampled_flag():
    assert parse(f"00-{'a' * 32}-{'b' * 16}-00").sampled is False
    assert parse(f"00-{'a' * 32}-{'b' * 16}-01").sampled is True


def test_child_keeps_trace_fresh_span():
    root = mint()
    kid = root.child()
    assert kid.trace_id == root.trace_id
    assert kid.span_id != root.span_id
    assert kid.parent_id == root.span_id


def test_accept_matrix(armed):
    fs = armed
    base = fs.c_accepted.value, fs.c_malformed.value, fs.c_minted.value
    # well-formed: accepted, counted
    ctx = fs.accept(mint().header())
    assert ctx is not None and fs.c_accepted.value == base[0] + 1
    # malformed at the root hop: counted AND re-minted (never guessed)
    ctx = fs.accept("not-a-traceparent")
    assert ctx is not None
    assert fs.c_malformed.value == base[1] + 1
    assert fs.c_minted.value == base[2] + 1
    # malformed mid-trace: counted, NOT minted (no invented roots)
    assert fs.accept("still-bad", mint_on_missing=False) is None
    assert fs.c_malformed.value == base[1] + 2
    assert fs.c_minted.value == base[2] + 1
    # absent mid-trace: simply untraced
    assert fs.accept(None, mint_on_missing=False) is None


# ---------------------------------------------------------------------------
# collector: offset math, merge, never-raise
# ---------------------------------------------------------------------------

def test_estimate_offset_hand_computed():
    # sent at 10.0, received at 10.4, server stamped 110.2:
    # midpoint 10.2 -> offset exactly 100.0, bound rtt/2 = 0.2
    off, bound = estimate_offset(10.0, 10.4, 110.2)
    assert off == pytest.approx(100.0)
    assert bound == pytest.approx(0.2)


def test_estimate_offset_asymmetry_stays_in_bound():
    # true offset 50.0; route fully asymmetric (all 0.4s rtt on the
    # request leg): server stamps at local 10.4 -> 60.4. The midpoint
    # estimate is off by 0.2 — exactly the advertised rtt/2 bound,
    # never past it.
    off, bound = estimate_offset(10.0, 10.4, 60.4)
    assert abs(off - 50.0) <= bound + 1e-12
    # degenerate clock weirdness: rtt clamps at 0, bound 0
    assert estimate_offset(5.0, 4.0, 10.0)[1] == 0.0


def test_merge_aligns_skewed_clocks():
    # process b's wall clock runs 100 s AHEAD; uncorrected, its records
    # sort after a's even though they happened first
    a = [{"ts": 10.0, "mono": 1.0, "name": "a0"},
         {"ts": 12.0, "mono": 3.0, "name": "a1"}]
    b = [{"ts": 109.0, "mono": 1.0, "name": "b0"},
         {"ts": 111.0, "mono": 3.0, "name": "b1"}]
    merged = merge_process_events({"a": a, "b": b}, offsets={"b": 100.0})
    assert [r["name"] for r in merged] == ["b0", "a0", "b1", "a1"]
    b0 = next(r for r in merged if r["name"] == "b0")
    assert b0["ts"] == pytest.approx(9.0)
    assert b0["ts_raw"] == pytest.approx(109.0)   # original preserved
    assert b0["src"] == "b"


def test_merge_mono_beats_ntp_step():
    # an NTP step INSIDE one process makes wall time jump backwards
    # mid-stream; mono is authoritative within the process, and the
    # corrected ts clamps non-decreasing so the merge cannot reorder
    recs = [{"ts": 100.0, "mono": 1.0, "name": "e0"},
            {"ts": 90.0, "mono": 2.0, "name": "e1"},    # step: -10 s
            {"ts": 91.0, "mono": 3.0, "name": "e2"}]
    merged = merge_process_events({"p": recs})
    assert [r["name"] for r in merged] == ["e0", "e1", "e2"]
    ts = [r["ts"] for r in merged]
    assert ts == sorted(ts)


def test_events_tail_tolerates_everything(tmp_path):
    assert fleetscope.events_tail("/nonexistent/nope.jsonl") == []
    p = tmp_path / "ev.jsonl"
    p.write_text('{"ts": 1, "name": "ok"}\nnot json\n'
                 '{"ts": 2, "name": "ok2"}\n')
    tail = fleetscope.events_tail(str(p), n=10)
    assert [r["name"] for r in tail] == ["ok", "ok2"]
    assert len(fleetscope.events_tail(str(p), n=1)) == 1


def test_join_traces_counts_unjoined():
    rtr = [{"name": "fleetscope.request",
            "args": {"trace_id": "t1", "replica": "r0", "status": 200}},
           {"name": "fleetscope.request",
            "args": {"trace_id": "t2", "replica": "r1", "status": 200}}]
    rep = [{"name": "serving.request", "args": {"trace_id": "t1"}}]
    joined = join_traces(rtr, rep)
    assert set(joined) == {"t1", "t2"}
    assert joined["t1"]["replica"] is not None
    assert joined["t1"]["replica_name"] == "r0"
    assert joined["t2"]["replica"] is None   # unjoined stays, counted


def test_collector_never_raises_on_dead_target():
    # a port with no listener: the pull must come back as a counted
    # error entry, never an exception on the control plane
    coll = Collector([{"name": "dead", "host": "127.0.0.1", "port": 9}],
                     timeout_s=0.5)
    before = coll._c_errors.value
    assert coll.poll_one(coll.targets[0]) is None
    assert coll.errors["dead"] is not None
    assert coll._c_errors.value == before + 1
    assert coll.poll_once() == []
    assert coll.snapshot()["processes"]["dead"]["pulls"] == 0


# ---------------------------------------------------------------------------
# off-path discipline
# ---------------------------------------------------------------------------

def test_off_path_is_one_predicate(frozen):
    fleetscope.disable()
    assert fleetscope._FS is None and not fleetscope.enabled()
    srv = ModelServer(frozen, max_delay_ms=1.0)
    host, port = srv.start()
    try:
        tp = mint()
        code, doc = _post(f"http://{host}:{port}/predict",
                          {"data": [0.0] * 6},
                          headers={"traceparent": tp.header()})
        assert code == 200
        # off: the header is never parsed, nothing echoes back
        assert "trace_id" not in doc
    finally:
        srv.stop()


def test_server_echoes_trace_id(frozen, armed):
    srv = ModelServer(frozen, max_delay_ms=1.0)
    host, port = srv.start()
    try:
        tp = mint()
        code, doc = _post(f"http://{host}:{port}/predict",
                          {"data": [0.0] * 6},
                          headers={"traceparent": tp.header()})
        assert code == 200
        assert doc.get("trace_id") == tp.trace_id
        # malformed header: counted, and NOT echoed (a mid-trace hop
        # never invents a trace)
        bad_before = armed.c_malformed.value
        code, doc = _post(f"http://{host}:{port}/predict",
                          {"data": [0.0] * 6},
                          headers={"traceparent": "bogus"})
        assert code == 200 and "trace_id" not in doc
        assert armed.c_malformed.value == bad_before + 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# end-to-end: router -> replica over a real HTTP hop, one trace
# ---------------------------------------------------------------------------

def test_fleet_propagation_e2e(frozen, armed, tmp_path):
    ev_path = tmp_path / "events.jsonl"
    hm_events.open_log(str(ev_path), run_id="fs-e2e", rank=0)
    rset = ReplicaSet(_factory, n=2,
                      server_kwargs={"max_delay_ms": 1.0})
    rset.start()
    router = Router(rset)
    host, port = router.start()
    sent = {}
    try:
        for i in range(6):
            tp = mint()
            code, doc = _post(f"http://{host}:{port}/predict",
                              {"data": [float(i)] * 6},
                              headers={"traceparent": tp.header()})
            assert code == 200
            # the router echoes the CLIENT's trace id back
            assert doc.get("trace_id") == tp.trace_id
            sent[tp.trace_id] = doc.get("replica")
    finally:
        router.stop()
        rset.stop()
        hm_events.close_log()

    recs = [json.loads(ln) for ln in ev_path.read_text().splitlines()]
    assert all(str(r["schema"]).startswith("mxtpu.events/") for r in recs)
    rtr = [r for r in recs if r["name"] == "fleetscope.request"]
    rep = [r for r in recs if r["name"] == "serving.request"
           and (r.get("args") or {}).get("trace_id")]
    joined = join_traces(rtr, rep)
    for tid in sent:
        slot = joined[tid]
        assert slot["router"] is not None and slot["replica"] is not None
        ra, pa = slot["router"]["args"], slot["replica"]["args"]
        # one trace, parent-linked across the hop
        assert pa["parent_id"] == ra["span_id"]
        assert slot["replica_name"] == sent[tid]
        # the wire gap is a difference of DURATIONS: router-observed
        # forward always covers the replica-observed e2e
        assert ra["forward_ms"] >= pa["e2e_ms"] - 0.5
    # batch records carry their member traces for the coalesce join
    batches = [r for r in recs if r["name"] == "serving.batch"]
    batched = {t for r in batches
               for t in (r["args"].get("traces") or [])}
    assert set(sent) <= batched


def test_trace_and_pod_render(frozen, armed, tmp_path, capsys):
    ev_path = tmp_path / "events.jsonl"
    hm_events.open_log(str(ev_path), run_id="fs-render", rank=0)
    rset = ReplicaSet(_factory, n=1,
                      server_kwargs={"max_delay_ms": 1.0})
    rset.start()
    router = Router(rset)
    host, port = router.start()
    tp = mint()
    try:
        code, doc = _post(f"http://{host}:{port}/predict",
                          {"data": [0.5] * 6},
                          headers={"traceparent": tp.header()})
        assert code == 200
    finally:
        router.stop()
        rset.stop()
        hm_events.close_log()
    mxdiag = _load_tool("mxdiag")
    assert mxdiag.main(["trace", tp.trace_id, str(ev_path)]) == 0
    out = capsys.readouterr().out
    assert tp.trace_id in out and "wire gap" in out
    # pod over a synthetic serve_load artifact
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({
        "metric": "serve_load_x_qps_at_knee", "value": 1.0,
        "extra": {"fleetscope": {
            "client_minted": 4, "sampled": 4, "joined": 3,
            "unjoined_forwards": 1, "join_rate": 0.75,
            "wire_gap_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "per_replica": [
                {"name": "r0", "traces": 2, "e2e_p99_ms": 5.0},
                {"name": "r1", "traces": 1, "e2e_p99_ms": 50.0}],
            "replica_spread": 10.0}}}))
    assert mxdiag.main(["pod", str(bench)]) == 0
    out = capsys.readouterr().out
    assert "straggler" in out and "join rate 75.0%" in out


# ---------------------------------------------------------------------------
# tooling contract: check_fleetscope_extra + build_fleetscope_extra
# ---------------------------------------------------------------------------

def _good_fs_extra():
    return {"client_minted": 10, "sampled": 8, "joined": 6,
            "unjoined_forwards": 2, "join_rate": 0.75,
            "wire_gap_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "per_replica": [{"name": "r0", "traces": 3,
                             "e2e_p99_ms": 4.0, "wire_gap_p50_ms": 1.0},
                            {"name": "r1", "traces": 3}],
            "replica_spread": 1.25}


def test_check_fleetscope_extra_good():
    tc = _load_tool("trace_check")
    assert tc.check_fleetscope_extra(_good_fs_extra()) == []
    assert tc.check_fleetscope_extra(None) == []
    # the optional blocks may be absent entirely (single-server mode)
    minimal = {"client_minted": 2, "sampled": 2, "joined": 2,
               "unjoined_forwards": 0, "join_rate": 1.0}
    assert tc.check_fleetscope_extra(minimal) == []


@pytest.mark.parametrize("mutate,needle", [
    (lambda d: d.update(joined=9), "exceeds"),
    (lambda d: d.update(join_rate=0.5), "disagrees"),
    (lambda d: d.update(join_rate=1.5), "join_rate"),
    (lambda d: d.update(sampled=-1), "sampled"),
    (lambda d: d.update(client_minted=None), "client_minted"),
    (lambda d: d["wire_gap_ms"].update(p50=9.0), "ordered"),
    (lambda d: d["wire_gap_ms"].update(p50=-5.0, p95=-2.0, p99=-1.5),
     "negative"),
    (lambda d: d["per_replica"].append({"name": "r0", "traces": 1}),
     "duplicate"),
    (lambda d: d["per_replica"].append({"name": "", "traces": 1}),
     "name"),
    (lambda d: d.update(replica_spread=0.5), "replica_spread"),
])
def test_check_fleetscope_extra_bad(mutate, needle):
    tc = _load_tool("trace_check")
    doc = _good_fs_extra()
    mutate(doc)
    errs = tc.check_fleetscope_extra(doc)
    assert errs and any(needle in e for e in errs), errs


def test_build_fleetscope_extra_assembly():
    sl = _load_tool("serve_load")
    rtr = [{"name": "fleetscope.request",
            "args": {"trace_id": f"t{i}", "replica": f"r{i % 2}",
                     "status": 200, "forward_ms": 10.0 + i,
                     "e2e_ms": 10.5 + i}}
           for i in range(4)]
    rtr.append({"name": "fleetscope.request",          # failed forward:
                "args": {"trace_id": "t9", "status": 503,   # not sampled
                         "e2e_ms": 1.0}})
    rep = [{"name": "serving.request",
            "args": {"trace_id": f"t{i}", "e2e_ms": 7.0 + i}}
           for i in range(3)]                          # t3 stays unjoined
    fs = sl.build_fleetscope_extra(6, rtr, rep)
    assert fs["client_minted"] == 6
    assert fs["sampled"] == 4 and fs["joined"] == 3
    assert fs["unjoined_forwards"] == 1
    assert fs["join_rate"] == pytest.approx(0.75)
    assert fs["wire_gap_ms"]["p50"] == pytest.approx(3.0)
    names = {r["name"]: r for r in fs["per_replica"]}
    assert names["r0"]["traces"] == 2 and names["r1"]["traces"] == 1
    assert fs["replica_spread"] >= 1.0
    # the section it emits is exactly what the validator enforces
    tc = _load_tool("trace_check")
    assert tc.check_fleetscope_extra(fs) == []


def test_build_fleetscope_extra_empty():
    sl = _load_tool("serve_load")
    fs = sl.build_fleetscope_extra(0, [], [])
    assert fs["sampled"] == 0 and fs["join_rate"] == 0.0
    assert "wire_gap_ms" not in fs and "per_replica" not in fs
    tc = _load_tool("trace_check")
    assert tc.check_fleetscope_extra(fs) == []


def test_elastic_telemetry_push_and_pod_view():
    """The training-side transport: members PUSH bounded telemetry over
    the membership wire (rank 0 cannot dial in), the coordinator's
    reply clock seeds the member's offset estimate, and the offset
    rides along on the NEXT report into pod_telemetry()."""
    from incubator_mxnet_tpu.profiler.counters import counter
    from incubator_mxnet_tpu.resilience import ElasticGroup

    g0 = ElasticGroup(rank=0, sync_timeout_s=5.0)
    g1 = ElasticGroup(rank=1, addr=g0.addr, sync_timeout_s=5.0)
    try:
        g0.join()
        g1.join()
        # first report: no offset yet; the reply's coordinator_ts
        # produces one (same host, so it is ~0 with a small rtt bound)
        r1 = g1.report_telemetry(counters={"io.records_read": 5},
                                 events_tail=[{"name": "x"}],
                                 health={"ok": True})
        assert r1 is not None
        assert isinstance(r1["offset_s"], float)
        assert r1["offset_bound_s"] >= 0
        assert abs(r1["offset_s"]) <= r1["offset_bound_s"] + 1.0
        # second report CARRIES the estimate to the coordinator
        g1.report_telemetry(counters={"io.records_read": 9})

        pod = g0.pod_telemetry()
        ring = pod["reports"][1]
        assert len(ring) == 2
        assert ring[0]["counters"] == {"io.records_read": 5}
        assert ring[0]["rank"] == 1 and "received_ts" in ring[0]
        assert ring[0]["offset_s"] is None          # pre-estimate
        assert ring[1]["offset_s"] == pytest.approx(r1["offset_s"])
        assert pod["offsets"][1] == pytest.approx(r1["offset_s"])
    finally:
        g1.leave()
        g0.leave()
    # the wire is gone: the push is a counted datum, never a raise
    before = counter("fleetscope.telem_errors", "fleetscope").value
    assert g1.report_telemetry(counters={}) is None
    assert counter("fleetscope.telem_errors",
                   "fleetscope").value == before + 1
