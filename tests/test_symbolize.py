"""Gluon -> Symbol tracing tests (gluon/symbolize.py).

Reference parity: upstream MXNet recovers a serializable graph from a
HybridBlock via hybrid_forward(F=mx.sym) inside _build_cache
(python/mxnet/gluon/block.py); here the same recovery happens by operator
dispatch when a block is called with Symbol inputs. These tests pin the
contract: traced graph == eager numerics, JSON round-trips, export/imports
interoperate, BatchNorm stats classify as aux.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.symbolize import trace_symbol


def _trace_parity(net, shape, atol=1e-5):
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).uniform(
        -1, 1, shape).astype("float32"))
    y_ref = net(x).asnumpy()
    sym, arg_p, aux_p = trace_symbol(net)
    y2 = sym.bind(args={"data": x, **arg_p},
                  aux_states=aux_p).forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(y_ref, y2, atol=atol, rtol=1e-5)
    return sym, arg_p, aux_p


class TestTraceParity:
    def test_mlp(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.5),
                nn.Dense(4))
        sym, arg_p, aux_p = _trace_parity(net, (2, 8))
        assert not aux_p
        assert len(arg_p) == 4

    def test_conv_bn_pool(self):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, layout="NCHW"),
                nn.BatchNorm(axis=1), nn.Activation("relu"),
                nn.MaxPool2D(2, layout="NCHW"),
                nn.GlobalAvgPool2D(layout="NCHW"), nn.Flatten(),
                nn.Dense(5))
        sym, arg_p, aux_p = _trace_parity(net, (2, 3, 8, 8))
        # running stats must be auxiliary states, not trainable args
        assert sorted(aux_p) == sorted(k for k in aux_p
                                       if k.endswith(("running_mean",
                                                      "running_var")))
        assert len(aux_p) == 2

    def test_activation_layers(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(8), nn.LeakyReLU(0.1), nn.Dense(8), nn.ELU(0.9),
                nn.Dense(8), nn.SELU(), nn.Dense(8), nn.GELU(),
                nn.Dense(8), nn.Swish(), nn.Dense(2))
        _trace_parity(net, (3, 6))

    def test_resnet18_traces_and_serializes(self):
        from incubator_mxnet_tpu.models import get_model
        net = get_model("resnet18_v1", classes=10, layout="NCHW")
        sym, arg_p, aux_p = _trace_parity(net, (1, 3, 32, 32))
        # serializable: round-trip through JSON preserves numerics
        x = mx.nd.array(np.random.RandomState(1).uniform(
            0, 1, (1, 3, 32, 32)).astype("float32"))
        sym2 = mx.sym.load_json(sym.tojson())
        y1 = sym.bind(args={"data": x, **arg_p},
                      aux_states=aux_p).forward(is_train=False)[0].asnumpy()
        y2 = sym2.bind(args={"data": x, **arg_p},
                       aux_states=aux_p).forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(y1, y2, atol=1e-6)

    @pytest.mark.slow
    def test_densenet_squeezenet_mobilenet(self):
        from incubator_mxnet_tpu.models import get_model
        for name in ("densenet121", "squeezenet1_0", "mobilenet1_0"):
            net = get_model(name, classes=10, layout="NCHW")
            _trace_parity(net, (1, 3, 64, 64))


class TestExportImports:
    def test_export_then_symbolblock_imports(self, tmp_path):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(4, 3, padding=1, layout="NCHW"),
                nn.BatchNorm(axis=1), nn.Activation("relu"), nn.Flatten(),
                nn.Dense(3))
        net.initialize()
        x = mx.nd.array(np.random.RandomState(0).uniform(
            0, 1, (2, 3, 8, 8)).astype("float32"))
        y_ref = net(x).asnumpy()

        path = os.path.join(str(tmp_path), "model")
        net.export(path, epoch=7)
        assert os.path.exists(path + "-symbol.json")
        assert os.path.exists(path + "-0007.params")

        block = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                          path + "-0007.params")
        y2 = block(x).asnumpy()
        np.testing.assert_allclose(y_ref, y2, atol=1e-5, rtol=1e-5)

    def test_export_to_onnx_chain(self, tmp_path):
        # gluon -> symbol -> onnx -> import: the full interchange chain
        from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet
        net = nn.HybridSequential()
        net.add(nn.Conv2D(4, 3, padding=1, layout="NCHW"),
                nn.Activation("relu"), nn.GlobalAvgPool2D(layout="NCHW"),
                nn.Flatten(), nn.Dense(3))
        net.initialize()
        x = mx.nd.array(np.random.RandomState(0).uniform(
            0, 1, (2, 3, 8, 8)).astype("float32"))
        y_ref = net(x).asnumpy()
        sym, arg_p, aux_p = trace_symbol(net)
        params = dict(arg_p)
        params.update(aux_p)
        fn = os.path.join(str(tmp_path), "m.onnx")
        onnx_mxnet.export_model(sym, params, [(2, 3, 8, 8)],
                                onnx_file_path=fn)
        sym2, arg2, aux2 = onnx_mxnet.import_model(fn)
        args = {"data": x}
        args.update(arg2)
        y2 = sym2.bind(args=args,
                       aux_states=aux2).forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(y_ref, y2, atol=1e-5, rtol=1e-4)


class TestRegressions:
    def test_scalar_parameter_stays_variable(self):
        # a 1-element Parameter used via `sym * p.data()` must become a
        # named Variable, NOT get baked into the graph as a constant
        # (float() coercion would freeze the checkpointed value)
        class Scaled(nn.HybridSequential):
            def __init__(self):
                super().__init__()
                self.scale = self.params.get("scale", shape=(1,),
                                             init="ones")

            def forward(self, x):
                return super().forward(x) * self.scale.data()

        net = Scaled()
        net.add(nn.Dense(3))
        net.initialize()
        net(mx.nd.array(np.zeros((1, 4), np.float32)))
        sym, arg_p, aux_p = trace_symbol(net)
        scale_name = [n for n in arg_p if n.endswith("scale")]
        assert scale_name, "scale parameter was baked in, not a Variable"
        # swap in a different value: output must track the new parameter
        x = mx.nd.array(np.ones((1, 4), np.float32))
        args = {"data": x}
        args.update(arg_p)
        y1 = sym.bind(args=args).forward(is_train=False)[0].asnumpy()
        args[scale_name[0]] = mx.nd.array(np.array([3.0], np.float32))
        y3 = sym.bind(args=args).forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(y3, 3.0 * y1, rtol=1e-6)

    def test_add_n_traces(self):
        class Three(nn.HybridSequential):
            def forward(self, x):
                from incubator_mxnet_tpu import ndarray as nd
                y = super().forward(x)
                return nd.add_n(y, y, y)

        net = Three()
        net.add(nn.Dense(4))
        _trace_parity(net, (2, 3))


class TestErrors:
    def test_uninitialized_raises(self):
        from incubator_mxnet_tpu.gluon.parameter import \
            DeferredInitializationError
        net = nn.Dense(4)
        with pytest.raises((DeferredInitializationError, RuntimeError)):
            trace_symbol(net)

    def test_constant_ndarray_in_forward_raises(self):
        class Weird(nn.HybridSequential):
            def forward(self, x):
                y = super().forward(x)
                return y + mx.nd.array(np.arange(2, dtype=np.float32))

        net = Weird()
        net.add(nn.Dense(2))
        net.initialize()
        net(mx.nd.array(np.zeros((1, 3), np.float32)))
        with pytest.raises(NotImplementedError, match="parameter"):
            trace_symbol(net)


class TestTransformerLMTracing:
    """Attention as a first-class symbol op (reference: the symbol-level
    interleaved_matmul/multihead ops of src/operator/contrib/
    transformer.cc) — the causal LM traces to a serializable graph."""

    def _lm(self):
        from incubator_mxnet_tpu.models import TransformerLM
        mx.random.seed(0)
        np.random.seed(0)
        m = TransformerLM(vocab_size=30, num_layers=2, units=32,
                          hidden_size=64, num_heads=4, max_length=16)
        m.initialize(init=mx.init.Xavier())
        return m

    def test_trace_parity_and_json_roundtrip(self):
        from incubator_mxnet_tpu import symbol as S
        m = self._lm()
        x = nd.array(np.random.RandomState(0).randint(0, 30, (2, 8))
                     .astype(np.float32))
        ref = m(x).asnumpy()
        sym, args, aux = trace_symbol(m, "data")
        out = sym.bind(mx.cpu(), {**args, "data": x}).forward()[0]
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)
        s2 = S.load_json(sym.tojson())
        out2 = s2.bind(mx.cpu(), {**args, "data": x}).forward()[0]
        np.testing.assert_allclose(out2.asnumpy(), ref, rtol=2e-5,
                                   atol=2e-5)

    def test_traced_lm_backward(self):
        m = self._lm()
        x = nd.array(np.random.RandomState(1).randint(0, 30, (2, 8))
                     .astype(np.float32))
        sym, args, aux = trace_symbol(m, "data")
        ex = sym.bind(mx.cpu(), {**args, "data": x},
                      args_grad={k: nd.zeros(v.shape)
                                 for k, v in args.items()})
        ex.forward(is_train=True)
        ex.backward(nd.ones(ex.outputs[0].shape))
        # the tied embedding weight must receive gradient through BOTH
        # uses (input lookup AND the transpose_b logits head)
        emb_name = [n for n in ex.grad_dict
                    if "embedding" in n and "pos" not in n]
        assert emb_name, sorted(ex.grad_dict)
        assert float(np.abs(
            ex.grad_dict[emb_name[0]].asnumpy()).sum()) > 0
        total = sum(float(np.abs(g.asnumpy()).sum())
                    for g in ex.grad_dict.values())
        assert total > 0


def test_sym_multihead_attention_direct():
    """sym.multihead_attention as a user-facing symbol op: parity with the
    nd op, causal + mask variants, JSON round-trip."""
    from incubator_mxnet_tpu import symbol as S
    from incubator_mxnet_tpu import ops

    rng = np.random.RandomState(0)
    q = nd.array(rng.randn(2, 6, 16).astype(np.float32))
    k = nd.array(rng.randn(2, 6, 16).astype(np.float32))
    v = nd.array(rng.randn(2, 6, 16).astype(np.float32))

    mask = nd.array((rng.rand(1, 1, 6, 6) > 0.3).astype(np.float32))
    for kwargs in ({}, {"causal": True}, {"scale": 0.5}, {"mask": mask}):
        feed = {"q": q, "k": k, "v": v}
        skw = dict(kwargs)
        if "mask" in skw:
            skw["mask"] = S.Variable("mask")
            feed["mask"] = mask
        s = S.multihead_attention(S.Variable("q"), S.Variable("k"),
                                  S.Variable("v"), num_heads=4, **skw)
        out = s.bind(mx.cpu(), feed).forward()[0]
        ref = ops.multihead_attention(q, k, v, 4, **kwargs)
        np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                   rtol=2e-5, atol=2e-5)
        s2 = __import__("incubator_mxnet_tpu").symbol.load_json(s.tojson())
        out2 = s2.bind(mx.cpu(), feed).forward()[0]
        np.testing.assert_allclose(out2.asnumpy(), out.asnumpy(), rtol=1e-6)


def test_sym_arange_like_and_dot_transpose():
    from incubator_mxnet_tpu import symbol as S

    d = nd.array(np.zeros((3, 7), np.float32))
    s = S.contrib.arange_like(S.Variable("d"), axis=1)
    out = s.bind(mx.cpu(), {"d": d}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), np.arange(7, dtype=np.float32))

    rng = np.random.RandomState(3)
    a = nd.array(rng.randn(4, 5).astype(np.float32))
    b = nd.array(rng.randn(6, 5).astype(np.float32))
    s = S.dot(S.Variable("a"), S.Variable("b"), transpose_b=True)
    out = s.bind(mx.cpu(), {"a": a, "b": b}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(),
                               a.asnumpy() @ b.asnumpy().T, rtol=1e-5)
    # nd path agrees
    np.testing.assert_allclose(
        nd.dot(a, b, transpose_b=True).asnumpy(),
        a.asnumpy() @ b.asnumpy().T, rtol=1e-5)


def test_traced_lm_overlength_fails_at_bind():
    """L > max_length must fail at bind (shape mismatch), never silently
    clamp positional embeddings."""
    from incubator_mxnet_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=20, num_layers=1, units=16,
                      hidden_size=32, num_heads=2, max_length=8)
    m.initialize(init=mx.init.Xavier())
    sym, args, aux = trace_symbol(m, "data")
    ok = nd.array(np.zeros((2, 8), np.float32))
    out = sym.bind(mx.cpu(), {**args, "data": ok}).forward()[0]
    assert out.shape == (2, 8, 20)
    too_long = nd.array(np.zeros((2, 12), np.float32))
    with pytest.raises(Exception):
        sym.bind(mx.cpu(), {**args, "data": too_long}).forward()[0].asnumpy()


def test_trace_warns_on_attention_dropout():
    from incubator_mxnet_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=20, num_layers=1, units=16,
                      hidden_size=32, num_heads=2, max_length=8,
                      dropout=0.1)
    m.initialize(init=mx.init.Xavier())
    with pytest.warns(UserWarning, match="dropout"):
        trace_symbol(m, "data")


def test_bert_traces_and_serializes():
    """BERT (encoder path) traces to a serializable symbol graph — the
    NLP deployment story alongside the CNN zoo and the causal LM."""
    from incubator_mxnet_tpu.models.bert import BERTModel
    mx.random.seed(0)
    np.random.seed(0)
    m = BERTModel(num_layers=2, units=32, hidden_size=64, num_heads=4,
                  max_length=16, vocab_size=50, dropout=0.0,
                  use_pooler=False)
    m.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randint(0, 50, (2, 10))
                 .astype(np.float32))
    ref = m(x).asnumpy()
    sym, args, aux = trace_symbol(m, "data")
    out = sym.bind(mx.cpu(), {**args, "data": x}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)
    s2 = mx.sym.load_json(sym.tojson())
    out2 = s2.bind(mx.cpu(), {**args, "data": x}).forward()[0]
    np.testing.assert_allclose(out2.asnumpy(), ref, rtol=2e-5, atol=2e-5)
    # valid_length cannot trace: clear error, not a crash
    with pytest.raises(ValueError, match="valid_length"):
        from incubator_mxnet_tpu.gluon.symbolize import SymbolizeScope
        from incubator_mxnet_tpu.symbol import Variable
        id2name = {id(p.data()): n for n, p in m.collect_params().items()}
        with SymbolizeScope(id2name):
            m(Variable("data"), valid_length=Variable("vl"))


def test_lm_export_symbolblock_imports(tmp_path):
    """HybridBlock.export -> SymbolBlock.imports deployment path for the
    causal LM (bit-exact)."""
    from incubator_mxnet_tpu.models import TransformerLM
    m = TransformerLM(vocab_size=40, num_layers=2, units=32,
                      hidden_size=64, num_heads=4, max_length=16)
    m.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randint(0, 40, (2, 8))
                 .astype(np.float32))
    ref = m(x).asnumpy()
    path = os.path.join(str(tmp_path), "lm")
    m.export(path, epoch=1)
    blk = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                    path + "-0001.params")
    np.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=2e-5,
                               atol=2e-5)
