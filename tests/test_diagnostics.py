"""mxtpu.diagnostics: memory ledger, metrics export, flight recorder,
thread-safe counters registry, and the trace_check validators for the new
artifact kinds."""
import gc
import importlib.util
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import diagnostics as diag
from incubator_mxnet_tpu import engine, gluon, nd
from incubator_mxnet_tpu import profiler as prof


def _trace_check():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_check.py")
    spec = importlib.util.spec_from_file_location("trace_check", path)
    tc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tc)
    return tc


@pytest.fixture(autouse=True)
def _diag_teardown():
    yield
    diag.disable()
    diag.reset_memory()


def _small_net():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _train_steps(net, trainer, n=2, batch=4):
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.rand(batch, 8).astype(np.float32))
    y = nd.array(np.random.randint(0, 4, batch))
    for _ in range(n):
        with mx.autograd.record():
            loss = L(net(x), y).mean()
        loss.backward()
        trainer.step(batch)
    return float(loss.asscalar())


# ---------------------------------------------------------------------------
# counters registry thread-safety (satellite)
# ---------------------------------------------------------------------------

class TestCountersThreadSafety:
    def test_concurrent_increments_are_exact(self):
        c = prof.counter("diag_test.conc", "test")
        c.set_value(0)
        c.kind = "counter"
        n_threads, n_incs = 8, 5000
        stop = threading.Event()

        def writer():
            for _ in range(n_incs):
                c.increment()

        def reader():
            # the sampler's view: snapshot while writers hammer the registry
            while not stop.is_set():
                snap = prof.counters()
                assert isinstance(snap.get("test/diag_test.conc"), int)

        threads = [threading.Thread(target=writer) for _ in range(n_threads)]
        r = threading.Thread(target=reader)
        r.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        r.join()
        assert c.value == n_threads * n_incs

    def test_kinds(self):
        c = prof.counter("diag_test.kind_c", "test")
        c.increment()
        prof.set_gauge("diag_test.kind_g", 1.5, "test")
        kinds = prof.counter_kinds()
        assert kinds["test/diag_test.kind_c"] == "counter"
        assert kinds["test/diag_test.kind_g"] == "gauge"
        snap = prof.registry_snapshot()
        assert snap["test/diag_test.kind_g"] == (1.5, "gauge")


# ---------------------------------------------------------------------------
# memory ledger
# ---------------------------------------------------------------------------

class TestMemoryLedger:
    def test_register_and_free_balance(self):
        diag.enable_memory(reset=True)
        x = nd.ones((64, 64))          # 16 KiB f32
        s = diag.memory_summary(include_reconcile=False)
        assert s["current_bytes"] == 64 * 64 * 4
        assert s["peak_bytes"] >= 64 * 64 * 4
        assert s["live_arrays"] == 1
        del x
        gc.collect()
        s = diag.memory_summary(include_reconcile=False)
        assert s["current_bytes"] == 0
        assert s["peak_bytes"] >= 64 * 64 * 4   # peak is sticky

    def test_alias_dedup(self):
        diag.enable_memory(reset=True)
        x = nd.ones((32, 32))
        y = x.detach()                 # same buffer, second wrapper
        s = diag.memory_summary(include_reconcile=False)
        assert s["current_bytes"] == 32 * 32 * 4
        assert s["live_arrays"] == 2
        del x
        gc.collect()
        s = diag.memory_summary(include_reconcile=False)
        assert s["current_bytes"] == 32 * 32 * 4   # y still holds it
        del y
        gc.collect()
        assert diag.memory_summary(
            include_reconcile=False)["current_bytes"] == 0

    def test_by_dtype_and_context(self):
        diag.enable_memory(reset=True)
        a = nd.ones((16, 16), dtype="float32")
        b = nd.ones((16, 16), dtype="int32")
        s = diag.memory_summary(include_reconcile=False)
        ctx = str(mx.current_context())
        assert s["by_context"][ctx]["current_bytes"] == 2 * 16 * 16 * 4
        assert s["by_dtype"][ctx]["float32"] == 16 * 16 * 4
        assert s["by_dtype"][ctx]["int32"] == 16 * 16 * 4
        del a, b

    def test_block_attribution(self):
        diag.enable_memory(reset=True)
        net = _small_net()
        x = nd.array(np.random.rand(4, 8).astype(np.float32))
        net(x)
        s = diag.memory_summary(include_reconcile=False)
        blocks = s["by_block"]
        # deferred-init params + activations were created inside the
        # Dense children's __call__ scopes
        assert any(k.startswith("dense_") for k in blocks), blocks

    def test_no_leak_after_del_model(self):
        """The acceptance invariant: current bytes return to (near)
        baseline once the model and trainer die."""
        diag.enable_memory(reset=True)
        base = diag.memory_summary(include_reconcile=False)["current_bytes"]
        net = _small_net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        _train_steps(net, trainer)
        mid = diag.memory_summary(include_reconcile=False)["current_bytes"]
        assert mid > base
        del net, trainer
        gc.collect()
        nd.waitall()
        end = diag.memory_summary(include_reconcile=False)["current_bytes"]
        # residue = the two input arrays created in _train_steps (well
        # under one parameter-set of the 16x8+16 + 4x16+4 net)
        assert end - base < 8 * 8 * 4 + 4 * 16 * 4 + 1024

    def test_bulk_deferred_arrays_accounted(self):
        diag.enable_memory(reset=True)
        with engine.bulk(8):
            x = nd.ones((8, 8))
            y = x * 2 + 1
            s = diag.memory_summary(include_reconcile=False)
            assert s["current_bytes"] >= 2 * 8 * 8 * 4  # deferred outputs too
        assert float(y.sum().asscalar()) == 3.0 * 64
        del x, y

    def test_inplace_mutation_keeps_ledger_truthful(self):
        """In-place __setitem__ swaps NDArray._data, freeing buffers whose
        ids CPython immediately recycles; the weakref-validated dedup must
        treat a recycled id as a new buffer, not an alias (would silently
        drop its bytes), and the ledger must return to zero at the end."""
        diag.enable_memory(reset=True)
        x = nd.ones((64,))
        for i in range(50):
            x[0] = float(i)
            s = diag.memory_summary(include_reconcile=False)
            assert s["current_bytes"] >= 64 * 4
            assert s["current_bytes"] <= 4 * 64 * 4, s["current_bytes"]
        del x
        gc.collect()
        assert diag.memory_summary(
            include_reconcile=False)["current_bytes"] == 0

    def test_reconcile_shape(self):
        diag.enable_memory(reset=True)
        rec = diag.reconcile()
        assert "devices" in rec and "jax_live_arrays" in rec

    def test_format_memory_summary(self):
        diag.enable_memory(reset=True)
        x = nd.ones((8, 8))
        out = diag.format_memory_summary()
        assert "current" in out and "peak" in out
        del x

    def test_disabled_is_free(self):
        diag.disable_memory()
        from incubator_mxnet_tpu import ndarray as nd_mod
        assert nd_mod._mem_hook is None


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

class TestExport:
    def test_prometheus_text_validates(self, tmp_path):
        diag.enable_memory(reset=True)
        prof.counter("diag_test.prom", "test").increment(3)
        nd.ones((4, 4))
        text = diag.prometheus_text()
        assert "# TYPE" in text
        p = tmp_path / "m.prom"
        p.write_text(text)
        tc = _trace_check()
        assert tc.check_prom(str(p)) == []

    def test_prom_counter_vs_gauge_types(self):
        prof.counter("diag_test.c2", "test").increment()
        prof.set_gauge("diag_test.g2", 7, "test")
        text = diag.prometheus_text()
        assert "# TYPE test_diag_test_c2 counter" in text
        assert "# TYPE test_diag_test_g2 gauge" in text

    def test_prom_large_counters_not_truncated(self):
        """%g-style 6-sig-digit formatting would render consecutive
        scrapes of a growing byte counter identically; values must
        round-trip exactly."""
        prof.counter("diag_test.big_bytes", "test").set_value(0)
        c = prof.counter("diag_test.big_bytes", "test")
        c.kind = "counter"
        c.increment(123456789)
        assert "test_diag_test_big_bytes 123456789.0" in \
            diag.prometheus_text()

    def test_prom_families_contiguous_across_contexts(self, tmp_path):
        """All samples of one metric family must form one contiguous
        group (strict OpenMetrics parsers reject a reopened family)."""
        snap = {"ts": 1.0, "counters": {}, "kinds": {},
                "memory": {"current_bytes": 3, "peak_bytes": 4,
                           "live_arrays": 2,
                           "by_context": {
                               "cpu(0)": {"current_bytes": 1,
                                          "peak_bytes": 2},
                               "tpu(0)": {"current_bytes": 2,
                                          "peak_bytes": 2}}}}
        lines = diag.prometheus_text(snap).splitlines()
        fams = [ln.split("{")[0] for ln in lines
                if ln and not ln.startswith("#")]
        seen, closed = set(), set()
        for f in fams:
            assert f not in closed, f"family {f} reopened"
            closed |= seen - {f}
            seen.add(f)
        p = tmp_path / "multi.prom"
        p.write_text(diag.prometheus_text(snap))
        assert _trace_check().check_prom(str(p)) == []

    def test_sampler_writes_monotonic_series(self, tmp_path):
        diag.enable_memory(reset=True)
        jsonl = str(tmp_path / "metrics.jsonl")
        promf = str(tmp_path / "metrics.prom")
        c = prof.counter("diag_test.sampled", "test")
        s = diag.start_sampler(interval_ms=20, jsonl_path=jsonl,
                               prom_path=promf)
        for _ in range(10):
            c.increment()
            time.sleep(0.015)
        diag.stop_sampler()
        assert not s.is_alive()
        assert s.ticks >= 2
        tc = _trace_check()
        assert tc.check_metrics_jsonl(jsonl) == []
        assert tc.check_prom(promf) == []
        lines = [json.loads(ln) for ln in open(jsonl) if ln.strip()]
        vals = [ln["counters"].get("test/diag_test.sampled", 0)
                for ln in lines]
        assert vals == sorted(vals)           # monotonic counter
        assert "memory" in lines[-1]          # ledger riding along

    def test_http_endpoint(self):
        diag.enable_memory(reset=True)
        server, port = diag.start_http(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read()
            assert b"# TYPE" in body
            js = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/json", timeout=10).read())
            assert "counters" in js and "ts" in js
            mem = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/memory", timeout=10).read())
            assert "current_bytes" in mem
        finally:
            diag.stop_http()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self, tmp_path):
        rec = diag.enable_flight_recorder(capacity=16, dump_on_crash=False,
                                          dump_dir=str(tmp_path))
        for i in range(100):
            diag.record("test", f"ev{i}")
        assert len(rec.events) == 16
        names = [e["name"] for e in rec.events]
        assert names[-1] == "ev99" and "ev0" not in names

    def test_subsystem_events_recorded(self, tmp_path):
        rec = diag.enable_flight_recorder(capacity=512,
                                          dump_on_crash=False,
                                          dump_dir=str(tmp_path))
        net = _small_net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        with engine.bulk(4):
            z = nd.ones((4, 4)) * 2 + 1
        float(z.sum().asscalar())
        _train_steps(net, trainer, n=1)
        kinds = {e["kind"] for e in rec.events}
        names = {e["name"] for e in rec.events}
        assert "op" in kinds                       # dispatch hook
        assert "trainer.step" in names
        assert any(n == "bulk.flush" for n in names)

    def test_dump_schema_valid(self, tmp_path):
        diag.enable_flight_recorder(capacity=64, dump_on_crash=False,
                                    dump_dir=str(tmp_path))
        nd.ones((4, 4))
        path = diag.dump_flight(reason="unit_test")
        tc = _trace_check()
        assert tc.check_flight(path) == []
        doc = json.load(open(path))
        assert doc["schema"].startswith("mxtpu.flight/")
        assert doc["reason"] == "unit_test"
        assert doc["counters"] and doc["env"]["pid"] == os.getpid()
        # auto-detection routes flight dumps correctly
        assert tc.check_file(path) == []

    def test_crash_dump_from_training_step_and_idempotent(self, tmp_path):
        """The crash path: an induced exception inside a training step
        reaches the installed excepthook, which writes a schema-valid
        dump; a second invocation is idempotent (same path, no rewrite)."""
        rec = diag.enable_flight_recorder(capacity=256, dump_on_crash=True,
                                          dump_dir=str(tmp_path))
        assert sys.excepthook is diag.flight._crash_excepthook
        net = _small_net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        L = gluon.loss.SoftmaxCrossEntropyLoss()
        x = nd.array(np.random.rand(4, 8).astype(np.float32))
        y = nd.array(np.random.randint(0, 4, 4))
        try:
            with mx.autograd.record():
                loss = L(net(x), y).mean()
            loss.backward()
            trainer.step(4)
            raise RuntimeError("induced mid-training failure")
        except RuntimeError:
            info = sys.exc_info()
        # simulate the interpreter's uncaught-exception path
        sys.excepthook(*info)
        path = diag.last_dump_path()
        assert path and os.path.exists(path)
        tc = _trace_check()
        assert tc.check_flight(path) == []
        doc = json.load(open(path))
        assert doc["reason"] == "uncaught:RuntimeError"
        assert doc["exception"]["type"] == "RuntimeError"
        assert "induced mid-training failure" in doc["exception"]["message"]
        names = {e["name"] for e in doc["events"]}
        assert "trainer.step" in names        # the seconds-before context
        assert rec.dump_count == 1
        # second crash-path dump: idempotent, no rewrite
        mtime = os.path.getmtime(path)
        sys.excepthook(*info)
        assert diag.last_dump_path() == path
        assert rec.dump_count == 1
        assert os.path.getmtime(path) == mtime

    def test_best_effort_dump_survives_held_registry_lock(self, tmp_path):
        """The SIGTERM path: a dump must complete even while another
        thread holds the counters-registry lock (the interrupted main
        thread may hold it — a blocking snapshot would deadlock the
        process inside its own signal handler)."""
        import importlib
        counters_mod = importlib.import_module(
            "incubator_mxnet_tpu.profiler.counters")
        rec = diag.enable_flight_recorder(capacity=32, dump_on_crash=False,
                                          dump_dir=str(tmp_path))
        diag.record("test", "pre-sigterm")
        done = {}

        def dump_under_lock():
            done["path"] = rec.dump(reason="SIGTERM", best_effort=True)

        with counters_mod._lock:      # simulate the interrupted holder
            t = threading.Thread(target=dump_under_lock)
            t.start()
            t.join(timeout=15)
            assert not t.is_alive(), "best-effort dump deadlocked"
        assert os.path.exists(done["path"])
        tc = _trace_check()
        assert tc.check_flight(done["path"]) == []

    def test_env_snapshot_keys(self, tmp_path):
        os.environ["MXTPU_DIAG_TEST_MARK"] = "42"
        try:
            diag.enable_flight_recorder(capacity=8, dump_on_crash=False,
                                        dump_dir=str(tmp_path))
            path = diag.dump_flight(reason="env")
            doc = json.load(open(path))
            assert doc["env"]["env"]["MXTPU_DIAG_TEST_MARK"] == "42"
            assert doc["env"]["jax_backend"] == "cpu"
        finally:
            del os.environ["MXTPU_DIAG_TEST_MARK"]

    def test_sigterm_chain_respects_sig_ign(self, tmp_path, monkeypatch):
        """A process that set SIGTERM to SIG_IGN chose to survive it; the
        dump handler must not convert that into process death (if it
        does, this very test run dies)."""
        import signal as signal_mod
        from incubator_mxnet_tpu.diagnostics import flight
        diag.enable_flight_recorder(capacity=8, dump_on_crash=False,
                                    dump_dir=str(tmp_path))
        monkeypatch.setattr(flight, "_prev_sigterm", signal_mod.SIG_IGN)
        flight._sigterm_handler(signal_mod.SIGTERM, None)   # must return
        path = diag.last_dump_path()
        assert path and json.load(open(path))["reason"] == "SIGTERM"

    def test_disabled_is_free(self):
        diag.disable_flight_recorder()
        from incubator_mxnet_tpu import ndarray as nd_mod
        assert nd_mod._flight_hook is None
        assert diag.dump_flight() is None


# ---------------------------------------------------------------------------
# validators: negative cases
# ---------------------------------------------------------------------------

class TestValidators:
    def test_bad_flight_dump_rejected(self, tmp_path):
        tc = _trace_check()
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"schema": "mxtpu.flight/1",
                                 "events": [{"kind": "x"}]}))
        errs = tc.check_flight(str(p))
        assert errs and any("ts" in e for e in errs)
        assert any("dumped_at" in e for e in errs)

    def test_backwards_ts_rejected(self, tmp_path):
        tc = _trace_check()
        p = tmp_path / "bad2.json"
        p.write_text(json.dumps({
            "schema": "mxtpu.flight/1", "dumped_at": 2.0, "reason": "r",
            "env": {}, "config": {}, "counters": {}, "counter_kinds": {},
            "events": [{"ts": 2.0, "kind": "a", "name": "a"},
                       {"ts": 1.0, "kind": "b", "name": "b"}]}))
        assert any("backwards" in e for e in tc.check_flight(str(p)))

    def test_non_monotonic_counter_rejected(self, tmp_path):
        tc = _trace_check()
        p = tmp_path / "m.jsonl"
        lines = [{"ts": 1.0, "counters": {"a/x": 5}, "kinds": {"a/x": "counter"}},
                 {"ts": 2.0, "counters": {"a/x": 3}, "kinds": {"a/x": "counter"}}]
        p.write_text("\n".join(json.dumps(x) for x in lines))
        assert any("decreased" in e for e in tc.check_metrics_jsonl(str(p)))
        # gauges may decrease freely
        for ln in lines:
            ln["kinds"]["a/x"] = "gauge"
        p.write_text("\n".join(json.dumps(x) for x in lines))
        assert tc.check_metrics_jsonl(str(p)) == []

    def test_bad_prom_rejected(self, tmp_path):
        tc = _trace_check()
        p = tmp_path / "bad.prom"
        p.write_text("# TYPE ok gauge\nok 1\n}}}garbage 2\n")
        assert any("malformed" in e for e in tc.check_prom(str(p)))
        p.write_text("no_type_decl 1\n")
        assert any("TYPE" in e for e in tc.check_prom(str(p)))

    def test_chrome_trace_still_validates(self, tmp_path):
        tc = _trace_check()
        p = tmp_path / "t.json"
        p.write_text(json.dumps({"traceEvents": [
            {"name": "a", "ph": "X", "ts": 0, "dur": 1, "pid": 0,
             "tid": 0}]}))
        assert tc.check_file(str(p)) == []

    def test_mxdiag_pretty_prints(self, tmp_path, capsys):
        diag.enable_flight_recorder(capacity=8, dump_on_crash=False,
                                    dump_dir=str(tmp_path))
        nd.ones((2, 2))
        path = diag.dump_flight(reason="print")
        base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "mxdiag", os.path.join(base, "tools", "mxdiag.py"))
        md = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(md)
        assert md.main([path, "--events", "3"]) == 0
        out = capsys.readouterr().out
        assert "flight dump" in out and "counters" in out


# ---------------------------------------------------------------------------
# integration: everything on at once, results unchanged, bounded overhead
# ---------------------------------------------------------------------------

class TestIntegration:
    def test_full_stack_does_not_change_numerics(self, tmp_path):
        np.random.seed(7)
        mx.random.seed(7)
        net = _small_net()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        ref = _train_steps(net, tr, n=3)

        np.random.seed(7)
        mx.random.seed(7)
        diag.enable(diag_dir=str(tmp_path), sampler_interval_ms=50)
        net2 = _small_net()
        tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                            {"learning_rate": 0.1})
        got = _train_steps(net2, tr2, n=3)
        diag.disable()
        assert got == pytest.approx(ref, rel=1e-6)

    def test_enable_disable_roundtrip(self, tmp_path):
        diag.enable(diag_dir=str(tmp_path), sampler_interval_ms=25)
        assert diag.enabled()
        assert diag.memory_enabled() and diag.flight_enabled()
        assert diag.sampler_running()
        diag.disable()
        assert not diag.enabled()

    def test_overhead_bounded(self):
        """Full diagnostics (ledger + flight ring) on a hybridized
        microloop: generous 60% bound here (the <5% acceptance number is
        for real bench steps, where per-step work dwarfs the hooks; this
        guards against accidental O(n) scans on the hot path)."""
        net = gluon.nn.Dense(32, in_units=32)
        net.initialize()
        net.hybridize()
        x = nd.ones((16, 32))

        def loop(n=150):
            t0 = time.perf_counter()
            for _ in range(n):
                y = net(x)
            y.wait_to_read()
            return time.perf_counter() - t0

        loop(30)                            # warmup / compile
        # the whole measurement is ~100 ms of sub-ms iterations: one
        # scheduler burp landing inside a loop pair fails it spuriously
        # (observed 6x under full-suite load with NOTHING on this path
        # changed). Re-measure once before believing a failure — a real
        # O(n) hot-path regression fails both rounds.
        for attempt in range(2):
            diag.disable()
            base = min(loop(), loop())
            diag.enable_memory(reset=True)
            diag.enable_flight_recorder(dump_on_crash=False)
            on = min(loop(), loop())
            if on < base * 1.6 + 0.05:
                break
        diag.disable()
        assert on < base * 1.6 + 0.05, (base, on)


# ---------------------------------------------------------------------------
# histogram edge cases + flight ring wraparound (healthmon PR satellites)
# ---------------------------------------------------------------------------

class TestHistogramEdges:
    def test_empty_snapshot_percentiles_are_none_and_valid(self):
        from incubator_mxnet_tpu.profiler.counters import Histogram
        h = Histogram("edge.empty", "test")
        v = h.value
        assert v["count"] == 0 and v["sum"] == 0.0
        assert v["min"] is None and v["max"] is None
        assert v["p50"] is None and v["p95"] is None and v["p99"] is None
        assert v["buckets"]["+Inf"] == 0
        assert all(c == 0 for c in v["buckets"].values())
        # the validator accepts an empty histogram (no percentile demand)
        tc = _trace_check()
        assert tc.check_histogram_snapshot(v) == []

    def test_single_bucket_overflow_observations(self):
        """One finite bound; every observation above it lands in the
        +Inf overflow bucket, percentiles clamp to the observed max."""
        from incubator_mxnet_tpu.profiler.counters import Histogram
        h = Histogram("edge.single", "test", bounds=(1.0,))
        for v in (5.0, 7.0, 9.0):
            h.observe(v)
        v = h.value
        assert v["count"] == 3
        assert v["buckets"][repr(1.0)] == 0      # nothing under the bound
        assert v["buckets"]["+Inf"] == 3
        assert v["min"] == 5.0 and v["max"] == 9.0
        assert 5.0 <= v["p50"] <= v["p95"] <= v["p99"] <= 9.0
        tc = _trace_check()
        assert tc.check_histogram_snapshot(v) == []

    def test_single_bucket_mixed_under_and_overflow(self):
        from incubator_mxnet_tpu.profiler.counters import Histogram
        h = Histogram("edge.mixed", "test", bounds=(10.0,))
        for v in (1.0, 2.0, 100.0):
            h.observe(v)
        v = h.value
        assert v["buckets"][repr(10.0)] == 2 and v["buckets"]["+Inf"] == 3
        assert v["p50"] <= 10.0 and v["p99"] <= 100.0
        assert _trace_check().check_histogram_snapshot(v) == []

    def test_observation_exactly_on_bound_counts_below(self):
        from incubator_mxnet_tpu.profiler.counters import Histogram
        h = Histogram("edge.onbound", "test", bounds=(1.0, 2.0))
        h.observe(1.0)
        v = h.value
        # Prometheus `le` convention: value == bound is IN that bucket
        assert v["buckets"][repr(1.0)] == 1


class TestFlightRingWraparound:
    def test_wraparound_under_concurrent_writers(self, tmp_path):
        """N threads push far more events than the ring holds, racing a
        concurrent dumper; every dump along the way must stay bounded,
        schema-valid, and time-ordered, and the final ring must hold
        exactly `capacity` of the newest events."""
        cap = 64
        rec = diag.enable_flight_recorder(capacity=cap,
                                          dump_on_crash=False,
                                          dump_dir=str(tmp_path),
                                          record_ops=False)
        stop = threading.Event()
        dumps = []

        def writer(k):
            for i in range(500):
                rec.append("t", f"w{k}.e{i}", {"i": i})

        def dumper():
            while not stop.is_set():
                dumps.append(rec.dump(
                    reason="race",
                    path=str(tmp_path / "race_dump.json")))
                time.sleep(0.002)

        d = threading.Thread(target=dumper)
        d.start()
        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        d.join()
        assert len(rec.events) == cap       # deque stayed bounded
        final = rec.dump(reason="final",
                         path=str(tmp_path / "final_dump.json"))
        tc = _trace_check()
        assert tc.check_flight(final) == []
        doc = json.load(open(final))
        assert doc["n_events"] == cap
        # the ring keeps the NEWEST events: every writer wrote 500, so
        # nothing from the early half of any writer's stream survives
        names = [e["name"] for e in doc["events"]
                 if e["name"].startswith("w")]
        assert names and all(int(n.split(".e")[1]) >= 500 - cap
                             for n in names)
        # every mid-race dump parsed too
        assert tc.check_flight(str(tmp_path / "race_dump.json")) == []

    def test_wraparound_preserves_event_integrity(self, tmp_path):
        """Records pushed while the ring wraps are whole objects — a torn
        append (kind without name, args from another event) would mean
        the lock-free hot path isn't actually safe."""
        cap = 32
        rec = diag.enable_flight_recorder(capacity=cap,
                                          dump_on_crash=False,
                                          dump_dir=str(tmp_path),
                                          record_ops=False)

        def writer(k):
            for i in range(300):
                rec.append(f"kind{k}", f"w{k}.e{i}", {"writer": k})

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for ev in list(rec.events):
            k = int(ev["kind"][4:])
            assert ev["name"].startswith(f"w{k}.e")
            assert ev["args"]["writer"] == k
