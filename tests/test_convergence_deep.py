"""Deep convergence (VERDICT r4 #10): train-to-plateau with logged
curves, beyond the 'loss decreases over tens of steps' smokes.

- ResNet-18 on synthetic CIFAR-shape data to a high-accuracy PLATEAU
  (parity: example/image-classification/train_cifar10.py's role).
- TransformerLM to a low-perplexity plateau on a learnable synthetic
  language (parity: the LM training scripts' ppl curves).

Both are slow-tier (RUN_SLOW=1): full-size-enough models, hundreds of
steps on the CPU test backend.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models import get_model

pytestmark = pytest.mark.slow


def _synthetic_cifar(classes=8, n_per_class=24, seed=0):
    """Separable 32x32x3 classes: fixed template + noise, NHWC."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(classes, 32, 32, 3).astype(np.float32)
    xs, ys = [], []
    for c in range(classes):
        noise = rng.randn(n_per_class, 32, 32, 3).astype(np.float32) * 0.25
        xs.append(templates[c][None] + noise)
        ys.append(np.full(n_per_class, c, np.int32))
    x, y = np.concatenate(xs), np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


def test_resnet18_synthetic_cifar_plateau():
    mx.random.seed(0)
    x_np, y_np = _synthetic_cifar()
    net = get_model("resnet18_v1", classes=8, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9,
                        "wd": 1e-4})
    B = 48
    x_all, y_all = nd.array(x_np), nd.array(y_np)
    rng = np.random.RandomState(1)
    accs, losses = [], []
    for step in range(120):
        sel = rng.randint(0, len(y_np), B)
        xb, yb = nd.array(x_np[sel]), nd.array(y_np[sel])
        with autograd.record():
            loss = L(net(xb), yb)
        loss.backward()
        tr.step(B)
        losses.append(float(loss.asnumpy().mean()))
        if (step + 1) % 20 == 0:
            pred = net(x_all).asnumpy().argmax(axis=1)
            accs.append(float((pred == y_np).mean()))
            print(f"resnet18 step {step + 1}: loss {losses[-1]:.4f} "
                  f"acc {accs[-1]:.3f}", flush=True)
    # high-accuracy plateau: ends high AND has stopped improving fast
    assert accs[-1] > 0.95, f"final acc {accs[-1]:.3f} <= 0.95 ({accs})"
    assert accs[-2] > 0.90, f"not a plateau: {accs}"
    assert np.mean(losses[-10:]) < 0.2, losses[-10:]


def _synthetic_language(vocab=24, n_seq=96, T=24, seed=0):
    """Deterministic-ish markov language: token t+1 = (a*t + b) % vocab
    per-sequence with 3 rules — learnable to low perplexity, not trivial."""
    rng = np.random.RandomState(seed)
    rules = [(1, 1), (2, 3), (3, 5)]
    data = np.zeros((n_seq, T), np.int64)
    for i in range(n_seq):
        a, b = rules[i % len(rules)]
        t = rng.randint(0, vocab)
        # first token encodes the rule so the model can infer it
        data[i, 0] = i % len(rules)
        data[i, 1] = t
        for j in range(2, T):
            t = (a * t + b) % vocab
            data[i, j] = t
    return data


def test_transformer_lm_perplexity_plateau():
    from incubator_mxnet_tpu.models import TransformerLM
    from incubator_mxnet_tpu.models.transformer_lm import lm_loss
    mx.random.seed(0)
    vocab, T = 24, 24
    data = _synthetic_language(vocab=vocab, T=T)
    net = TransformerLM(vocab_size=vocab, num_layers=2, units=64,
                        hidden_size=128, num_heads=4, max_length=T)
    net.initialize(init=mx.init.Normal(0.02))
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adamw",
                       {"learning_rate": 3e-3})
    B = 32
    rng = np.random.RandomState(1)
    ppls = []
    for epoch in range(14):
        ep_losses = []
        for _ in range(len(data) // B):
            xb = nd.array(data[rng.randint(0, len(data), B)])
            with autograd.record():
                loss = lm_loss(net(xb), xb).mean()
            loss.backward()
            tr.step(B)
            ep_losses.append(float(loss.asnumpy()))
        ppls.append(float(np.exp(np.mean(ep_losses))))
        print(f"lm epoch {epoch}: ppl {ppls[-1]:.2f}", flush=True)
    # perplexity curve: big early drop, low plateau at the end
    assert ppls[0] > 2 * ppls[-1], ppls
    assert ppls[-1] < 2.0, f"final ppl {ppls[-1]:.2f} (curve: {ppls})"
    assert abs(ppls[-1] - ppls[-3]) < 0.35 * ppls[-1], \
        f"not plateaued: {ppls[-3:]}"
