"""mxtpu.devicescope: trace ingestion against a checked-in real XLA:CPU
artifact (lane parsing, busy-fraction math, top-K program join, gap
classification edge cases — parser never raises), the windowed capture
lifecycle, StepBudget provenance upgrade/fallback pinned both ways, the
drift warning, the healthmon post-mortem attach, and the tooling
satellites (trace_check DEVICESCOPE_FAMILIES + check_devicescope_extra,
perf_regress busy-fraction gate incl. the 0→nonzero window transition,
mxdiag perf/device rendering)."""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import devicescope as ds
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu import perfscope as ps
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu.devicescope import ingest
from incubator_mxnet_tpu.profiler import tpu as prof_tpu

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "devicescope_trace_cpu.json.gz")


def _load_tool(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _devicescope_teardown():
    # provenance isolation: an earlier test's published sharding layout
    # legitimately flips StepBudget's collective_source to
    # "unavailable" (the PR 9 semantics) — these tests pin the
    # UNSHARDED contracts, so start from a clean registry both ways
    from incubator_mxnet_tpu.parallel import sharding as shmod
    shmod.clear_mesh()
    shmod._LAST.clear()
    yield
    ds.disable()          # stops any still-active window
    ds.reset()
    ps.disable()
    ps.reset_programs()
    shmod.clear_mesh()
    shmod._LAST.clear()
    assert not prof_tpu.tracing(), \
        "a test leaked an active jax profiler trace"


def _counters(prefix="devicescope/"):
    return {k: v for k, v in prof.counters().items()
            if k.startswith(prefix)}


# ---------------------------------------------------------------------------
# ingestion: the checked-in real XLA:CPU artifact
# ---------------------------------------------------------------------------

class TestFixtureIngestion:
    """The fixture is a REAL `jax.profiler.trace` artifact: 3 steps of a
    dp4 (4 fake CPU devices) matmul+tanh+all-reduce train-ish step named
    jit_step_fn, captured on XLA:CPU (see tests/fixtures/)."""

    def test_load_trace_events(self):
        events, path = ingest.load_trace_events(FIXTURE)
        assert path == FIXTURE
        assert len(events) > 100

    def test_lane_parsing(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        ops, lanes = ingest.device_events(events)
        assert len(ops) > 50
        # every op is normalized and carries its module join key
        assert all(o["module"] == "jit_step_fn" for o in ops)
        assert all(o["dur"] >= 0 for o in ops)
        # lane metadata resolved from the M events
        assert len(lanes) >= 2
        assert any("tf_" in m["thread"] or "python" in m["thread"]
                   for m in lanes.values())
        kinds = {o["op"] for o in ops}
        assert "all-reduce" in kinds
        assert "dot" in kinds
        # trailing ".N" instance ids are stripped into op families
        assert not any(o["op"].split(".")[-1].isdigit() for o in ops)

    def test_summarize_busy_fraction_and_collectives(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        s = ingest.summarize(events, wall_ms=50.0, steps=3)
        assert s["device_events"] > 50
        assert 0.0 < s["busy_fraction"] <= 1.0
        assert s["busy_ms"] > 0
        # busy is a UNION: concurrent lanes can't exceed the wall
        assert s["busy_ms"] <= 50.0 + 1e-6 or s["busy_fraction"] == 1.0
        per = s["per_step"]
        assert per["device_busy_ms"] == pytest.approx(s["busy_ms"] / 3)
        kinds = {r["kind"] for r in s["collectives"]["by_kind"]}
        assert kinds == {"all-reduce"}
        assert s["collectives"]["union_ms"] > 0
        # union of collective intervals <= their plain sum (4 fake
        # devices run the same all-reduce concurrently)
        assert s["collectives"]["union_ms"] <= s["collectives"]["sum_ms"]
        assert per["collective_ms"] > 0

    def test_top_k_join_to_program_table(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        s = ingest.summarize(
            events, wall_ms=50.0, steps=3,
            program_map={"jit_step_fn": "fused_step"},
            programs=[{"name": "fused_step", "verdict": "hbm_bound"}])
        assert s["top_ops"], "top-K must be nonempty on a real artifact"
        assert all(t["program"] == "fused_step" for t in s["top_ops"])
        assert all(t["verdict"] == "hbm_bound" for t in s["top_ops"])
        # ranked by total device time, descending
        totals = [t["total_ms"] for t in s["top_ops"]]
        assert totals == sorted(totals, reverse=True)
        assert all(t["count"] >= 1 for t in s["top_ops"])

    def test_unjoined_module_keeps_null_program(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        s = ingest.summarize(events, wall_ms=50.0, steps=3,
                             program_map={"some_other_module": "x"})
        assert all(t["program"] is None for t in s["top_ops"])
        assert all(t["verdict"] is None for t in s["top_ops"])

    def test_collective_axis_join_via_commscope_inventory(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        comms = [{"name": "fused_step",
                  "collectives": [{"kind": "all-reduce", "axis": "dp"}]}]
        s = ingest.summarize(events, wall_ms=50.0, steps=3,
                             program_map={"jit_step_fn": "fused_step"},
                             comms_programs=comms)
        row = s["collectives"]["by_kind"][0]
        assert row["kind"] == "all-reduce"
        assert row["axis"] == "dp"

    def test_axis_by_kind_api(self):
        # the join rule's one home: commscope.axis_by_kind (record or
        # captured-name form; unknown -> {}, ambiguity -> None)
        from incubator_mxnet_tpu import commscope as cs
        rec = {"name": "p", "collectives": [
            {"kind": "all-reduce", "axis": "dp"},
            {"kind": "all-gather", "axis": "dp"},
            {"kind": "all-to-all", "axis": "dp"},
            {"kind": "all-to-all", "axis": "mp"}]}
        m = cs.axis_by_kind(rec)
        assert m == {"all-reduce": "dp", "all-gather": "dp",
                     "all-to-all": None}
        assert cs.axis_by_kind("never-captured-program") == {}
        assert cs.axis_by_kind(None) == {}

    def test_ambiguous_axis_is_none(self):
        events, _ = ingest.load_trace_events(FIXTURE)
        comms = [{"name": "fused_step",
                  "collectives": [{"kind": "all-reduce", "axis": "dp"},
                                  {"kind": "all-reduce", "axis": "mp"}]}]
        s = ingest.summarize(events, wall_ms=50.0, steps=3,
                             program_map={"jit_step_fn": "fused_step"},
                             comms_programs=comms)
        assert s["collectives"]["by_kind"][0]["axis"] is None


# ---------------------------------------------------------------------------
# ingestion: synthetic edge cases (the parser never raises)
# ---------------------------------------------------------------------------

def _x(ts, dur, name, pid=1, tid=1, module="jit_m", hlo=True):
    ev = {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
          "name": name}
    if hlo:
        ev["args"] = {"hlo_op": name, "hlo_module": module}
    return ev


class TestIngestEdgeCases:
    def test_empty_trace(self):
        s = ingest.summarize([], wall_ms=10.0, steps=2)
        assert s["busy_fraction"] == 0.0
        assert s["top_ops"] == []
        assert s["device_events"] == 0
        assert s["per_step"]["device_busy_ms"] == 0.0

    def test_single_event(self):
        s = ingest.summarize([_x(0.0, 4000.0, "dot.1")],
                             wall_ms=10.0, steps=1)
        assert s["busy_fraction"] == pytest.approx(0.4)
        assert s["top_ops"][0]["op"] == "dot"
        assert s["gaps"]["count"] == 0

    def test_overlapping_lanes_union_not_sum(self):
        # two lanes 100% busy over the same 5 ms: union is 5 ms, not 10
        evs = [_x(0.0, 5000.0, "dot.1", tid=1),
               _x(0.0, 5000.0, "dot.2", tid=2)]
        s = ingest.summarize(evs, wall_ms=5.0, steps=1)
        assert s["busy_ms"] == pytest.approx(5.0)
        assert s["busy_fraction"] == pytest.approx(1.0)

    def test_missing_metadata_never_raises(self):
        # no M events at all; events missing args/ts/dur/name; garbage
        evs = [{"ph": "X", "pid": 1, "tid": 1, "name": "dot",
                "args": {"hlo_op": "dot"}},            # no ts/dur
               {"ph": "X", "ts": "NaNish", "dur": 1.0,
                "args": {"hlo_op": "x"}},              # non-numeric ts
               {"ph": "X", "ts": 1.0, "dur": -5.0,
                "args": {"hlo_op": "y"}},              # negative dur
               {"ph": "M", "name": "thread_name"},     # argless meta
               {"ph": "X", "ts": 0.0, "dur": 1000.0, "name": "ok.1",
                "args": {"hlo_op": "ok.1"}},
               "not even a dict" if False else {"ph": "B"},
               {"args": {"hlo_op": "no-ph"}}]
        s = ingest.summarize(evs, wall_ms=2.0, steps=1)
        assert s["device_events"] == 1
        assert s["top_ops"][0]["op"] == "ok"

    def test_garbage_wall_and_steps(self):
        evs = [_x(0.0, 1000.0, "dot")]
        s = ingest.summarize(evs, wall_ms=None, steps=0)
        # no wall: device span is the fallback denominator
        assert s["busy_fraction"] == pytest.approx(1.0)
        s2 = ingest.summarize(evs, wall_ms="junk", steps=None)
        assert s2["device_events"] == 1

    def test_unreadable_artifact(self, tmp_path):
        evs, f = ingest.load_trace_events(str(tmp_path / "missing"))
        assert evs == [] and f is None
        p = tmp_path / "torn.trace.json"
        p.write_text('{"traceEvents": [ {"truncated": ')
        evs, f = ingest.load_trace_events(str(p))
        assert evs == [] and f == str(p)

    def test_gap_classification(self):
        # three 1 ms ops with 2 ms gaps between: 2 gaps, 4 ms total
        evs = [_x(0.0, 1000.0, "a"), _x(3000.0, 1000.0, "b"),
               _x(6000.0, 1000.0, "c")]
        s = ingest.summarize(evs, wall_ms=10.0, steps=1,
                             counters_delta={"io_wait_ms": 2.0,
                                             "dispatch_ms": 3.0})
        g = s["gaps"]
        assert g["count"] == 2
        assert g["total_ms"] == pytest.approx(4.0)
        assert g["max_ms"] == pytest.approx(2.0)
        assert g["histogram_ms"]["10.0"] == 2
        # idle = 10 - 3 busy = 7; io covers 2, dispatch 3, residual 2
        tax = g["taxonomy"]
        assert tax["input_starved_ms"] == pytest.approx(2.0)
        assert tax["dispatch_serialized_ms"] == pytest.approx(3.0)
        assert tax["host_gap_ms"] == pytest.approx(2.0)
        assert sum(tax.values()) == pytest.approx(s["idle_ms"])

    def test_union_intervals_handcomputed(self):
        merged, total = ingest.union_intervals(
            [(5, 7), (0, 2), (1, 3), (10, 10)])
        assert merged == [(0, 3), (5, 7)]
        assert total == pytest.approx(5.0)

    def test_collective_kind_of(self):
        assert ingest.collective_kind_of("all-reduce.5") == "all-reduce"
        assert ingest.collective_kind_of("all-gather-start.2") \
            == "all-gather"
        assert ingest.collective_kind_of("all-to-all") == "all-to-all"
        assert ingest.collective_kind_of("reduce-scatter.1") \
            == "reduce-scatter"
        assert ingest.collective_kind_of("collective-permute-start") \
            == "collective-permute"
        assert ingest.collective_kind_of("dot.3") is None
        assert ingest.collective_kind_of("reduce.8") is None


# ---------------------------------------------------------------------------
# windowed capture lifecycle
# ---------------------------------------------------------------------------

def _run_jit_steps(n=3):
    f = jax.jit(lambda a: jnp.tanh(a @ a).sum())
    x = jnp.ones((64, 64), jnp.float32)
    float(f(x))                       # compile outside the window
    return f, x


class TestCaptureWindow:
    def test_capture_stops_at_requested_steps(self, tmp_path):
        f, x = _run_jit_steps()
        win = ds.capture(steps=2, logdir=str(tmp_path / "w"))
        win.start()
        assert win.active
        assert ds.active_window() is win
        for _ in range(5):
            float(f(x))
            win.step(1)
        # stopped itself at step 2; later marks were no-ops
        assert not win.active
        assert win.steps_done == 2
        assert ds.active_window() is None
        assert ds.last_window() is win
        s = win.summary()
        assert s["window"]["steps"] == 2
        assert s["window"]["complete"] is True
        assert 0.0 < s["busy_fraction"] <= 1.0
        assert s["top_ops"]
        assert _counters()["devicescope/devicescope.windows"] >= 1

    def test_context_manager_early_stop(self, tmp_path):
        f, x = _run_jit_steps()
        with ds.capture(steps=100, logdir=str(tmp_path / "w")) as win:
            float(f(x))
            win.step(1)
        assert not win.active
        s = win.summary()
        assert s["window"]["steps"] == 1
        assert s["window"]["complete"] is False    # early stop, honest
        assert s["busy_fraction"] is not None

    def test_concurrent_window_declines(self, tmp_path):
        f, x = _run_jit_steps()
        w1 = ds.capture(steps=10, logdir=str(tmp_path / "a")).start()
        assert w1.active
        before = _counters().get("devicescope/devicescope.declined", 0)
        w2 = ds.capture(steps=10, logdir=str(tmp_path / "b")).start()
        assert w2.state == "declined"
        assert _counters()["devicescope/devicescope.declined"] \
            == before + 1
        # a declined window creates NOTHING on disk — it must never
        # count against (or evict artifacts from) the rotation budget
        assert not os.path.exists(str(tmp_path / "b"))
        w2.step(1)                      # all no-ops, never raise
        w2.stop()
        assert w2.summary() is None
        w1.stop()
        assert ds.last_window() is w1

    def test_summary_is_lazy_and_cached(self, tmp_path):
        f, x = _run_jit_steps()
        win = ds.capture(steps=1, logdir=str(tmp_path / "w")).start()
        float(f(x))
        win.step(1)
        assert win._summary is None     # ingestion deferred out of loop
        s1 = win.summary()
        assert s1 is win.summary()      # cached
        assert ds.window_summary() is s1

    def test_rotation_bounds_artifact_dirs(self, tmp_path):
        base = tmp_path / "rot"
        base.mkdir()
        for i in range(5):
            d = base / f"win_old_{i}"
            d.mkdir()
            (d / "x").write_text("x")
            t = time.time() - 100 + i
            os.utime(d, (t, t))
        from incubator_mxnet_tpu.devicescope import window as wmod
        n = wmod.rotate_dirs(str(base), keep=3)
        assert n == 3
        left = sorted(p.name for p in base.iterdir())
        assert left == ["win_old_3", "win_old_4"]
        # keep honors MXTPU_DEVICESCOPE_KEEP when not passed explicitly
        assert wmod.rotate_dirs(str(base)) == 0

    def test_window_off_means_no_state(self):
        assert ds.window_summary() is None
        assert ds.last_window_path() is None
        assert ds.bench_extra()["window"] is None

    def test_async_dispatch_sync_barrier_captures_work(self, tmp_path):
        """Async dispatch: without the boundary sync the window can
        close with its own steps still in flight (zero device events);
        the per-mark `sync` barrier fixes exactly that — so a window
        over fully-async marks WITH the barrier must capture events."""
        f = jax.jit(lambda a: jnp.tanh(a @ a).sum())
        x = jnp.ones((64, 64), jnp.float32)
        float(f(x))
        win = ds.capture(steps=3, logdir=str(tmp_path / "w")).start()
        v = None
        for _ in range(3):
            v = f(x)                       # NO fetch: dispatch only
            win.step(1, sync=lambda: float(v))
        assert not win.active
        s = win.summary()
        assert s["device_events"] > 0
        assert s["per_step"]["device_busy_ms"] > 0

    def test_trainloop_marks_active_window(self, tmp_path):
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        L = gluon.loss.L2Loss()
        opt = mx.optimizer.create("sgd", learning_rate=0.01)
        loop = mx.TrainLoop(net, L, opt, chunk=2)
        xs = nd.array(np.random.rand(2, 4, 8).astype(np.float32))
        ys = nd.array(np.random.rand(2, 4, 4).astype(np.float32))
        loop.run_chunk(xs, ys)          # compile outside the window
        win = ds.capture(steps=4, logdir=str(tmp_path / "w")).start()
        loop.run_chunk(xs, ys)          # marks 2 steps itself
        assert win.steps_done == 2
        loop.run_chunk(xs, ys)
        assert not win.active           # bounded at 4
        assert win.summary()["window"]["steps"] == 4
        # no double-count: run_chunk already feeds trainloop.dispatch_ms,
        # so the window's dispatch delta must be the COUNTER delta alone
        # (the caller-accumulated channel is for counter-less loops)
        assert win.dispatch_ms == 0.0
        ctr = prof.counters().get("trainloop/trainloop.dispatch_ms")
        assert win._counters_delta["dispatch_ms"] <= float(ctr) + 1e-6

    def test_profile_xla_session_never_steals_window_trace(self, tmp_path):
        """set_state(profile_xla=True) must not stop a trace a
        devicescope window owns — jax allows one per process, and a
        failed start confers no right to stop."""
        from incubator_mxnet_tpu import profiler as profmod
        f, x = _run_jit_steps()
        win = ds.capture(steps=2, logdir=str(tmp_path / "w")).start()
        assert win.active
        profmod.set_config(profile_xla=True,
                           xla_logdir=str(tmp_path / "xla"))
        try:
            profmod.start()             # start declined (window owns it)
            profmod.stop()              # must NOT stop the window trace
            assert prof_tpu.tracing(), \
                "profiler session killed the window's trace"
            for _ in range(2):
                float(f(x))
                win.step(1)
            s = win.summary()
            assert s["device_events"] > 0       # capture survived intact
        finally:
            profmod.set_config(profile_xla=False)


# ---------------------------------------------------------------------------
# program join map (perfscope compile-site hook)
# ---------------------------------------------------------------------------

class TestProgramJoin:
    def test_module_name_of(self):
        def my_step(a):
            return a + 1
        low = jax.jit(my_step).lower(
            jax.ShapeDtypeStruct((4,), jnp.float32))
        assert ds.module_name_of(low) == "jit_my_step"
        assert ds.module_name_of(object()) is None

    def test_module_collision_poisons_join(self):
        # HLO module names are not unique (every hybridized Block jits
        # `raw_fn` → `jit_raw_fn`): a collision must unjoin, not pick
        # whichever program compiled last
        ds.enable()
        ds.register_program("jit:dense0:64x8", "jit_raw_fn")
        assert ds.program_map()["jit_raw_fn"] == "jit:dense0:64x8"
        ds.register_program("jit:dense0:64x8", "jit_raw_fn")  # re-analysis
        assert ds.program_map()["jit_raw_fn"] == "jit:dense0:64x8"
        ds.register_program("jit:dense1:32x4", "jit_raw_fn")  # collision
        assert ds.program_map()["jit_raw_fn"] is None
        ds.register_program("jit:dense0:64x8", "jit_raw_fn")
        assert ds.program_map()["jit_raw_fn"] is None  # stays poisoned
        # a poisoned key renders as an unjoined op, never a guess
        events, _ = ingest.load_trace_events(FIXTURE)
        s = ingest.summarize(events, wall_ms=50.0, steps=3,
                             program_map={"jit_step_fn": None})
        assert all(t["program"] is None for t in s["top_ops"])

    def test_fused_step_registers_module(self):
        ps.enable()
        ds.enable()
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        L = gluon.loss.L2Loss()
        opt = mx.optimizer.create("sgd", learning_rate=0.01)
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        step = FusedTrainStep(net, L, opt)
        x = nd.array(np.random.rand(4, 8).astype(np.float32))
        y = nd.array(np.random.rand(4, 4).astype(np.float32))
        float(step(x, y))
        assert ds.program_map().get("jit_step_fn") == "fused_step"

    def test_disabled_no_registration(self):
        ps.enable()
        assert ds._DS is None
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        L = gluon.loss.L2Loss()
        opt = mx.optimizer.create("sgd", learning_rate=0.01)
        from incubator_mxnet_tpu.parallel import FusedTrainStep
        step = FusedTrainStep(net, L, opt)
        x = nd.array(np.random.rand(4, 8).astype(np.float32))
        y = nd.array(np.random.rand(4, 4).astype(np.float32))
        float(step(x, y))
        assert ds.program_map() == {}


# ---------------------------------------------------------------------------
# step-budget reconciliation (provenance pinned both ways)
# ---------------------------------------------------------------------------

def _fake_summary(busy_per_step, coll_per_step, busy_fraction=0.5):
    return {"per_step": {"device_busy_ms": busy_per_step,
                         "collective_ms": coll_per_step,
                         "idle_ms": 1.0},
            "busy_fraction": busy_fraction,
            "window": {"path": "/tmp/fake_win", "steps": 5}}


class TestBudgetReconciliation:
    def _budget(self, steps=4, steady_s=0.4):
        ps.enable()
        b = ps.StepBudget().begin()
        b.end(steps=steps, steady_s=steady_s)
        return b

    def test_no_window_falls_back_exactly_as_today(self):
        b = self._budget()
        d = b.finish()
        assert d["source"] == "residual"
        assert d["collective_source"] == "measured"
        assert d["reconciliation"] is None

    def test_devicescope_off_never_overrides(self, monkeypatch):
        # even with a (stale) summary lying around, an unarmed
        # devicescope must not touch the budget
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(50.0, 0.0))
        assert ds._DS is None
        d = self._budget().finish()
        assert d["source"] == "residual"
        assert d["reconciliation"] is None

    def test_window_upgrades_provenance(self, monkeypatch):
        ds.enable()
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(80.0, 0.0))
        d = self._budget().finish()       # step_ms = 100
        assert d["source"] == "measured(profile)"
        assert d["device_compute_ms"] == pytest.approx(80.0)
        # measured 0 collective does NOT override the kvstore path
        assert d["collective_source"] == "measured"
        r = d["reconciliation"]
        assert r is not None
        assert r["measured"]["device_compute_ms"] == pytest.approx(80.0)
        assert r["analytic"]["source"] == "residual"
        # components still sum to the step wall
        total = sum(d[k] for k in ("device_compute_ms", "collective_ms",
                                   "input_wait_ms", "host_gap_ms",
                                   "other_ms"))
        assert total == pytest.approx(d["step_ms"], rel=1e-6)

    def test_measured_collective_upgrades_collective_source(
            self, monkeypatch):
        ds.enable()
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(80.0, 12.0))
        d = self._budget().finish()
        assert d["collective_source"] == "measured(profile)"
        assert d["collective_ms"] == pytest.approx(12.0)
        # busy minus its collective share: never double-counted
        assert d["device_compute_ms"] == pytest.approx(68.0)

    def test_drift_warning_fires_over_threshold(self, monkeypatch):
        ds.enable()
        before = _counters().get(
            "devicescope/devicescope.drift_warnings", 0)
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(80.0, 0.0))
        b = self._budget()
        b.probe(lambda: time.sleep(0.0005))   # analytic ~0.5 ms/step
        with pytest.warns(UserWarning, match="devicescope"):
            d = b.finish()
        assert d["reconciliation"]["drift_warning"] is True
        assert _counters()["devicescope/devicescope.drift_warnings"] \
            > before

    def test_no_drift_warning_under_threshold(self, monkeypatch):
        import warnings as _w
        ds.enable()
        fake = _fake_summary(100.0, 0.0)
        monkeypatch.setattr(ds, "window_summary", lambda: fake)
        b = self._budget()                   # step_ms=100; measured=100
        with _w.catch_warnings():
            _w.simplefilter("error")
            d = b.finish()
        r = d["reconciliation"]
        assert r["drift_warning"] is False
        # reconciliation lands in the window summary for extra.devicescope
        assert fake["reconciliation"] is r

    def test_overheated_window_still_sums_to_step_wall(self, monkeypatch):
        # a traced step pays profiler overhead, so the window's busy
        # time can exceed the UNTRACED steady per-step wall — the
        # settled components must still sum to step_ms
        ds.enable()
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(150.0, 60.0))
        with pytest.warns(UserWarning):
            d = self._budget().finish()        # step_ms = 100
        assert d["collective_ms"] == pytest.approx(60.0)
        assert d["device_compute_ms"] == pytest.approx(40.0)
        total = sum(d[k] for k in ("device_compute_ms", "collective_ms",
                                   "input_wait_ms", "host_gap_ms",
                                   "other_ms"))
        assert total == pytest.approx(d["step_ms"], rel=1e-6)

    def test_overlapped_input_wait_yields_to_measured_device(
            self, monkeypatch):
        # prefetch wait that OVERLAPS measured device busy must not
        # double-claim wall time: with busy 95/step and io.wait 40/step
        # on a 100 ms step, input_wait keeps only the 5 ms the device
        # was actually idle — the components still sum to step_ms and
        # trace_check keeps accepting the artifact
        ds.enable()
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(95.0, 0.0))
        b = self._budget()                   # step_ms = 100
        b._snap0["io/io.wait_ms"] = 0.0
        b._snap1["io/io.wait_ms"] = 160.0    # 40 ms/step over 4 steps
        d = b.finish()
        assert d["device_compute_ms"] == pytest.approx(95.0)
        assert d["input_wait_ms"] == pytest.approx(5.0)
        total = sum(d[k] for k in ("device_compute_ms", "collective_ms",
                                   "input_wait_ms", "host_gap_ms",
                                   "other_ms"))
        assert total == pytest.approx(d["step_ms"], rel=1e-6)

    def test_busy_zero_window_never_overrides(self, monkeypatch):
        ds.enable()
        monkeypatch.setattr(ds, "window_summary",
                            lambda: _fake_summary(0.0, 0.0))
        d = self._budget().finish()
        assert d["source"] == "residual"
        assert d["reconciliation"] is None

    def test_stale_window_never_upgrades_a_later_budget(self, tmp_path):
        """A window completed BEFORE a budget began measured someone
        else's steady phase — it must not be presented as that budget's
        measured truth (the strongest provenance on a wrong number)."""
        ps.enable()
        f, x = _run_jit_steps()
        with ds.capture(steps=1, logdir=str(tmp_path / "w")) as win:
            float(f(x))
            win.step(1)
        assert ds.window_summary()["busy_fraction"] is not None
        # a NEW budget begins after that window completed
        b = ps.StepBudget().begin()
        b.end(steps=4, steady_s=0.4)
        d = b.finish()
        assert d["source"] == "residual"
        assert d["reconciliation"] is None

    def test_serving_stamped_window_never_upgrades_a_train_budget(
            self, tmp_path):
        """A fresh window stepped by the SERVING batcher (train and
        serve share a process) measured dispatches this train budget
        never issued — workload identity, not just freshness, gates
        the measured(profile) upgrade. A 'mixed' window is rejected
        the same way; an unstamped (None) one stays accepted."""
        ps.enable()
        f, x = _run_jit_steps()
        b = ps.StepBudget().begin()
        with ds.capture(steps=1, logdir=str(tmp_path / "w")) as win:
            float(f(x))
            win.step(1, workload="serving")
        assert ds.window_summary()["busy_fraction"] is not None
        assert ds.last_window().workload == "serving"
        b.end(steps=4, steady_s=0.4)
        d = b.finish()
        assert d["source"] == "residual"
        assert d["reconciliation"] is None

    def test_mixed_steppers_degrade_window_to_mixed(self, tmp_path):
        with ds.capture(steps=5, logdir=str(tmp_path / "w")) as win:
            win.step(1, workload="train")
            win.step(1, workload="serving")
            win.step(1)                    # unstamped mark: no change
        assert win.workload == "mixed"

    def test_end_to_end_real_window(self, tmp_path):
        """A REAL capture window around real jit steps upgrades a real
        budget — the full measured path with no monkeypatching."""
        ps.enable()
        f, x = _run_jit_steps()
        b = ps.StepBudget().begin()
        win = ds.capture(steps=3, logdir=str(tmp_path / "w")).start()
        t0 = time.perf_counter()
        for _ in range(3):
            td = time.perf_counter()
            # fetch per step: a mark must only land once its device work
            # is DONE, or the auto-stop at step N can close the trace
            # with step N still in flight (async dispatch)
            float(f(x))
            b.add_dispatch(time.perf_counter() - td)
            win.step(1)
        b.end(steps=3, steady_s=time.perf_counter() - t0)
        win.stop()
        d = b.finish()
        assert d["source"] == "measured(profile)"
        assert d["device_compute_ms"] > 0
        assert d["reconciliation"]["measured"]["busy_fraction"] > 0


# ---------------------------------------------------------------------------
# healthmon post-mortems attach the window path
# ---------------------------------------------------------------------------

class TestHealthmonAttach:
    def test_nan_and_stall_alerts_carry_window_path(self, tmp_path,
                                                    monkeypatch):
        from incubator_mxnet_tpu import healthmon as hm
        monkeypatch.setattr(ds, "last_window_path",
                            lambda: "/tmp/mxtpu_devicescope/win_x")
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        run_id="r-test", rank=0)
        try:
            mon.observe_loss(float("nan"))
            mon.regress.observe(5.0)    # prime the EWMA path
        finally:
            hm.disable()
        recs = [json.loads(ln) for ln in
                open(os.path.join(str(tmp_path), "events_rank0.jsonl"))]
        nan = [r for r in recs if r["name"] == "healthmon.nan_loss"]
        assert nan and nan[0]["args"]["devicescope_window"] \
            == "/tmp/mxtpu_devicescope/win_x"

    def test_no_window_no_key(self, tmp_path):
        from incubator_mxnet_tpu import healthmon as hm
        assert ds.last_window_path() is None
        mon = hm.enable(hm_dir=str(tmp_path), stall_timeout_s=0,
                        run_id="r-test", rank=0)
        try:
            mon.observe_loss(float("inf"))
        finally:
            hm.disable()
        recs = [json.loads(ln) for ln in
                open(os.path.join(str(tmp_path), "events_rank0.jsonl"))]
        nan = [r for r in recs if r["name"] == "healthmon.nan_loss"]
        assert nan and "devicescope_window" not in nan[0]["args"]


# ---------------------------------------------------------------------------
# trace_check: counter family + extra.devicescope schema
# ---------------------------------------------------------------------------

def _valid_extra():
    return {
        "window": {"path": "/tmp/w", "steps": 10, "requested_steps": 10,
                   "wall_ms": 120.5, "complete": True},
        "busy_fraction": 0.42,
        "per_step": {"device_busy_ms": 5.0, "collective_ms": 0.5,
                     "idle_ms": 7.0},
        "top_ops": [{"op": "dot", "count": 10, "total_ms": 30.0,
                     "module": "jit_step_fn", "program": "fused_step",
                     "verdict": "compute_bound"}],
        "collectives": {"union_ms": 5.0, "sum_ms": 20.0,
                        "by_kind": [{"kind": "all-reduce", "count": 10,
                                     "total_ms": 20.0, "axis": "dp"}]},
        "gaps": {"count": 3, "total_ms": 2.0, "max_ms": 1.0,
                 "histogram_ms": {"0.1": 1, "1.0": 2, "10.0": 0,
                                  "100.0": 0, "+Inf": 0},
                 "taxonomy": {"input_starved_ms": 1.0,
                              "dispatch_serialized_ms": 0.5,
                              "host_gap_ms": 0.5}},
        "reconciliation": {
            "analytic": {"device_compute_ms": 6.0, "collective_ms": 0.6,
                         "collective_source": "estimated",
                         "source": "probe"},
            "measured": {"device_compute_ms": 4.5, "collective_ms": 0.5,
                         "busy_fraction": 0.42},
            "drift": {"device_compute": 0.25, "collective": None},
            "threshold": 0.25, "drift_warning": False},
    }


class TestTraceCheck:
    def test_families_accept_known_reject_unknown(self):
        tc = _load_tool("trace_check")
        ok = {k: v for k, v in tc.DEVICESCOPE_FAMILIES.items()}
        assert tc.check_healthmon_kinds(ok) == []
        bad = dict(ok)
        bad["devicescope/devicescope.made_up"] = "counter"
        assert any("made_up" in e for e in tc.check_healthmon_kinds(bad))
        flipped = dict(ok)
        flipped["devicescope/devicescope.windows"] = "gauge"
        assert any("kind" in e for e in tc.check_healthmon_kinds(flipped))

    def test_collective_sources_include_measured_profile(self):
        tc = _load_tool("trace_check")
        assert "measured(profile)" in tc.COLLECTIVE_SOURCES
        errs = tc.check_perfscope_extra({
            "peaks": {"peak_flops_f32": 1.0, "peak_flops_bf16": 2.0,
                      "hbm_bytes_per_s": 1.0},
            "programs": [],
            "decomposition": {"step_ms": 10.0, "device_compute_ms": 10.0,
                              "collective_ms": 0.0, "input_wait_ms": 0.0,
                              "host_gap_ms": 0.0, "other_ms": 0.0,
                              "collective_source": "measured(profile)"}})
        assert errs == []

    def test_valid_extra_passes(self):
        tc = _load_tool("trace_check")
        assert tc.check_devicescope_extra(_valid_extra()) == []
        assert tc.check_devicescope_extra(None) == []

    def test_zero_step_window_validates(self, tmp_path):
        # a window stopped before any mark is honest, not malformed
        tc = _load_tool("trace_check")
        f, x = _run_jit_steps()
        with ds.capture(steps=5, logdir=str(tmp_path / "w")):
            float(f(x))                 # work, but no step mark
        extra = ds.bench_extra()
        assert extra["window"]["steps"] == 0
        assert tc.check_devicescope_extra(extra) == []

    def test_armed_no_window_shape(self):
        tc = _load_tool("trace_check")
        assert tc.check_devicescope_extra(
            {"window": None, "busy_fraction": None, "per_step": None,
             "top_ops": [], "gaps": None, "reconciliation": None}) == []
        errs = tc.check_devicescope_extra(
            {"window": None, "busy_fraction": 0.5})
        assert any("null" in e for e in errs)

    def test_invalid_shapes_rejected(self):
        tc = _load_tool("trace_check")
        e = _valid_extra()
        e["busy_fraction"] = 1.7
        assert any("busy_fraction" in x
                   for x in tc.check_devicescope_extra(e))
        e = _valid_extra()
        e["top_ops"][0]["count"] = 0
        assert any("count" in x for x in tc.check_devicescope_extra(e))
        e = _valid_extra()
        e["collectives"]["by_kind"][0]["kind"] = "warp-shuffle"
        assert any("warp-shuffle" in x
                   for x in tc.check_devicescope_extra(e))
        e = _valid_extra()
        del e["gaps"]["taxonomy"]["host_gap_ms"]
        assert any("host_gap_ms" in x
                   for x in tc.check_devicescope_extra(e))
        e = _valid_extra()
        e["reconciliation"]["drift_warning"] = "yes"
        assert any("drift_warning" in x
                   for x in tc.check_devicescope_extra(e))
        e = _valid_extra()
        e["top_ops"][0]["verdict"] = "gpu_bound"
        assert any("gpu_bound" in x
                   for x in tc.check_devicescope_extra(e))

    def test_bench_json_wiring(self, tmp_path):
        tc = _load_tool("trace_check")
        doc = {"metric": "m", "value": 1.0, "unit": "x",
               "extra": {"mfu": 0.1, "devicescope": _valid_extra()}}
        p = tmp_path / "BENCH_ok.json"
        p.write_text(json.dumps(doc))
        assert tc.check_bench_json(str(p)) == []
        doc["extra"]["devicescope"]["busy_fraction"] = -2
        p2 = tmp_path / "BENCH_bad.json"
        p2.write_text(json.dumps(doc))
        assert any("devicescope" in e
                   for e in tc.check_bench_json(str(p2)))


# ---------------------------------------------------------------------------
# perf_regress: measured busy-fraction gate
# ---------------------------------------------------------------------------

def _artifact(tmp_path, name, value=100.0, busy=None):
    doc = {"metric": "m", "value": value, "unit": "img/s", "extra": {}}
    if busy is not None:
        doc["extra"]["devicescope"] = {"busy_fraction": busy}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestPerfRegressBusyGate:
    def _load(self, pr, path):
        rec, why = pr.load_artifact(path)
        assert rec is not None, why
        return rec

    def test_drop_beyond_threshold_regresses(self, tmp_path):
        pr = _load_tool("perf_regress")
        b = self._load(pr, _artifact(tmp_path, "b.json", busy=0.50))
        c = self._load(pr, _artifact(tmp_path, "c.json", busy=0.40))
        regs, _notes = pr.compare(b, c)
        assert any("busy fraction" in r for r in regs)

    def test_small_drop_ok(self, tmp_path):
        pr = _load_tool("perf_regress")
        b = self._load(pr, _artifact(tmp_path, "b.json", busy=0.50))
        c = self._load(pr, _artifact(tmp_path, "c.json", busy=0.48))
        regs, notes = pr.compare(b, c)
        assert not any("busy" in r for r in regs)
        assert any("busy fraction" in n for n in notes)

    def test_zero_to_nonzero_window_transition_skips(self, tmp_path):
        # the FIRST run that carries a window must not be indicted for
        # measuring (baseline has no devicescope data at all)
        pr = _load_tool("perf_regress")
        b = self._load(pr, _artifact(tmp_path, "b.json", busy=None))
        c = self._load(pr, _artifact(tmp_path, "c.json", busy=0.05))
        regs, notes = pr.compare(b, c)
        assert regs == []
        assert any("busy gate skipped" in n for n in notes)
        # ... and symmetrically when the candidate dropped its window
        regs2, notes2 = pr.compare(c, b)
        assert regs2 == []
        assert any("busy gate skipped" in n for n in notes2)

    def test_threshold_is_configurable(self, tmp_path):
        pr = _load_tool("perf_regress")
        b = self._load(pr, _artifact(tmp_path, "b.json", busy=0.50))
        c = self._load(pr, _artifact(tmp_path, "c.json", busy=0.40))
        regs, _ = pr.compare(b, c, busy_threshold=0.5)
        assert not any("busy" in r for r in regs)


# ---------------------------------------------------------------------------
# mxdiag rendering
# ---------------------------------------------------------------------------

class TestMxdiag:
    def _bench_doc(self):
        return {
            "metric": "m", "value": 100.0, "unit": "img/s",
            "extra": {
                "model": "lenet", "batch": 64, "dtype": "float32",
                "mfu": 0.1,
                "perfscope": {
                    "peaks": {"device_kind": "cpu", "table_row": "cpu",
                              "peak_flops_f32": 5e10,
                              "peak_flops_bf16": 5e10,
                              "hbm_bytes_per_s": 2e10},
                    "programs": [],
                    "decomposition": {
                        "step_ms": 10.0, "device_compute_ms": 4.5,
                        "collective_ms": 0.5, "input_wait_ms": 0.0,
                        "host_gap_ms": 2.0, "other_ms": 3.0,
                        "collective_source": "measured(profile)",
                        "source": "measured(profile)", "steps": 50,
                        "coverage": 1.0,
                        "reconciliation":
                            _valid_extra()["reconciliation"]},
                },
                "devicescope": _valid_extra(),
            },
        }

    def test_perf_renders_both_sources(self, capsys):
        md = _load_tool("mxdiag")
        assert md.print_perf(self._bench_doc()) == 0
        out = capsys.readouterr().out
        assert "[measured: devicescope window]" in out
        assert "analytic vs measured" in out
        assert "device_compute" in out
        # both numbers visible, not just one source
        assert "6.000" in out and "4.500" in out

    def test_perf_keeps_unavailable_tag(self, capsys):
        md = _load_tool("mxdiag")
        doc = self._bench_doc()
        d = doc["extra"]["perfscope"]["decomposition"]
        d["collective_source"] = "unavailable"
        d["reconciliation"] = None
        md.print_perf(doc)
        out = capsys.readouterr().out
        assert "UNAVAILABLE" in out

    def test_perf_renders_drift_warning(self, capsys):
        md = _load_tool("mxdiag")
        doc = self._bench_doc()
        rec = doc["extra"]["perfscope"]["decomposition"]["reconciliation"]
        rec["drift_warning"] = True
        rec["drift"]["device_compute"] = 0.6
        md.print_perf(doc)
        out = capsys.readouterr().out
        assert "DRIFT WARNING" in out
        assert "<< DRIFT" in out

    def test_device_renders_summary(self, capsys):
        md = _load_tool("mxdiag")
        assert md.print_device(self._bench_doc()) == 0
        out = capsys.readouterr().out
        assert "busy fraction: 42.0%" in out
        assert "top device ops" in out
        assert "all-reduce" in out
        assert "input-starved" in out
        # the SHARED reconciliation renderer (one home for perf+device)
        assert "analytic vs measured" in out

    def test_device_without_section(self, capsys):
        md = _load_tool("mxdiag")
        doc = self._bench_doc()
        del doc["extra"]["devicescope"]
        assert md.print_device(doc) == 1
        assert "BENCH_DEVICESCOPE=1" in capsys.readouterr().out

    def test_device_armed_no_window(self, capsys):
        md = _load_tool("mxdiag")
        doc = self._bench_doc()
        doc["extra"]["devicescope"] = {"window": None}
        assert md.print_device(doc) == 1
        assert "no capture window" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# bench payload shape
# ---------------------------------------------------------------------------

class TestBenchExtra:
    def test_armed_no_window_shape_validates(self):
        tc = _load_tool("trace_check")
        ds.enable()
        assert tc.check_devicescope_extra(ds.bench_extra()) == []

    def test_real_window_shape_validates(self, tmp_path):
        tc = _load_tool("trace_check")
        f, x = _run_jit_steps()
        with ds.capture(steps=2, logdir=str(tmp_path / "w")) as win:
            for _ in range(2):
                float(f(x))
                win.step(1)
        extra = ds.bench_extra()
        assert tc.check_devicescope_extra(extra) == []
        assert extra["window"]["steps"] == 2
        assert extra["top_ops"]
