"""Decoder-only TransformerLM: causal masking, KV-cache decode parity,
generation, training (reference: GluonNLP language-model scripts)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.models import (TransformerLM, lm_loss,
                                        transformer_lm_small)
from incubator_mxnet_tpu.models import get_model


def _model(vocab=50, **kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("units", 32)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_length", 32)
    m = TransformerLM(vocab, **kw)
    m.initialize(init=mx.init.Xavier())
    return m


def test_forward_shape_and_registry():
    m = _model()
    out = m(nd.array(np.zeros((3, 7))))
    assert out.shape == (3, 7, 50)
    z = get_model("transformer_lm_small", vocab_size=100, max_length=16)
    z.initialize()
    assert z(nd.array(np.zeros((1, 4)))).shape == (1, 4, 100)


def test_causal_masking_is_real():
    """Changing a future token must not change past logits."""
    m = _model()
    a = np.random.RandomState(0).randint(0, 50, (1, 8)).astype(np.float32)
    b = a.copy()
    b[0, -1] = (b[0, -1] + 1) % 50
    la = m(nd.array(a)).asnumpy()
    lb = m(nd.array(b)).asnumpy()
    np.testing.assert_allclose(la[:, :-1], lb[:, :-1], atol=1e-6)
    assert np.abs(la[:, -1] - lb[:, -1]).max() > 1e-4


def test_step_decode_matches_full_forward():
    m = _model()
    prompt = nd.array(np.random.RandomState(1).randint(
        0, 50, (2, 6)).astype(np.float32))
    full = m(prompt).asnumpy()
    caches = m.init_cache(2)
    for t in range(6):
        lg, caches = m._step_with_cache(prompt[:, t:t + 1], t, caches)
        np.testing.assert_allclose(lg.asnumpy(), full[:, t], atol=1e-4)


def test_generate_cache_matches_recompute():
    """Greedy generation with KV caches must equal naive re-forward."""
    m = _model()
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 50, (2, 4)).astype(np.float32)
    out = m.generate(prompt, 5).asnumpy()

    seq = prompt.copy()
    for _ in range(5):
        logits = m(nd.array(seq)).asnumpy()[:, -1]
        nxt = logits.argmax(-1).astype(np.float32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_generate_sampling_and_limits():
    m = _model()
    prompt = np.zeros((1, 4), np.float32)
    out = m.generate(prompt, 3, temperature=1.0, seed=7)
    assert out.shape == (1, 7)
    # deterministic under the same seed
    out2 = m.generate(prompt, 3, temperature=1.0, seed=7)
    np.testing.assert_array_equal(out.asnumpy(), out2.asnumpy())
    with pytest.raises(ValueError, match="max_length"):
        m.generate(np.zeros((1, 30), np.float32), 10)
    with pytest.raises(ValueError, match="max_length"):
        m(nd.array(np.zeros((1, 40))))


def test_tied_and_untied_heads():
    tied = _model(tie_weights=True)
    untied = _model(tie_weights=False)
    n_tied = sum(int(np.prod(p.shape))
                 for p in tied.collect_params().values())
    n_untied = sum(int(np.prod(p.shape))
                   for p in untied.collect_params().values())
    assert n_untied > n_tied  # separate (D,V) head + bias


def test_lm_trains_on_repeating_pattern():
    """A cyclic sequence is perfectly predictable: loss must collapse and
    greedy generation must continue the cycle."""
    vocab, period = 12, 4
    m = _model(vocab=vocab, max_length=24, num_layers=2, units=64,
               hidden_size=128)
    trainer = gluon.Trainer(m.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    seq = np.tile(np.arange(period), 5)[None, :20].astype(np.float32)
    x = nd.array(np.repeat(seq, 4, axis=0))
    first = last = None
    for i in range(150):
        with mx.autograd.record():
            loss = lm_loss(m(x), x)
        loss.backward()
        trainer.step(4)
        v = float(loss.mean().asnumpy())
        first = v if first is None else first
        last = v
    assert last < first * 0.2, (first, last)
    out = m.generate(seq[:, :6], period).asnumpy()[0, 6:]
    expect = [(6 + i) % period for i in range(period)]
    np.testing.assert_array_equal(out, expect)


class TestSequenceParallelLM:
    """Long-context causal LM over the sp mesh axis: ring and ulysses
    cores must match dense causal attention exactly."""

    def _build(self, ring, vocab=40):
        mx.random.seed(0)
        np.random.seed(0)
        return TransformerLM(vocab, num_layers=2, units=32, hidden_size=64,
                             num_heads=8, max_length=64, ring=ring)

    @pytest.mark.parametrize("scheme", ["ring", "ulysses"])
    def test_matches_dense(self, scheme):
        from incubator_mxnet_tpu.parallel import make_mesh
        mesh = make_mesh({"sp": 8})
        ids = np.random.RandomState(0).randint(0, 40, (2, 64)).astype(
            np.float32)
        dense = self._build(None)
        dense.initialize()
        ref = dense(nd.array(ids)).asnumpy()
        par = self._build((mesh, "sp", scheme))
        par.initialize()  # same seeds -> same params
        got = par(nd.array(ids)).asnumpy()
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_generate_refuses_ring(self):
        from incubator_mxnet_tpu.parallel import make_mesh
        mesh = make_mesh({"sp": 8})
        m = self._build((mesh, "sp"))
        m.initialize()
        with pytest.raises(ValueError, match="single-device"):
            m.generate(np.zeros((1, 4), np.float32), 2)

    def test_ring_lm_trains(self):
        from incubator_mxnet_tpu.parallel import make_mesh
        mesh = make_mesh({"sp": 8})
        m = self._build((mesh, "sp"))
        m.initialize()
        trainer = gluon.Trainer(m.collect_params(), "adam",
                                {"learning_rate": 1e-2})
        ids = nd.array(np.random.RandomState(1).randint(
            0, 40, (2, 64)).astype(np.float32))
        losses = []
        for _ in range(8):
            with mx.autograd.record():
                loss = lm_loss(m(ids), ids).mean()
            loss.backward()
            trainer.step(2)
            losses.append(float(loss.asnumpy()))
        assert losses[-1] < losses[0]
