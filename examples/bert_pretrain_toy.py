"""Toy BERT MLM+NSP pretraining loop (the GluonNLP scripts/bert shape),
optionally with ring-attention sequence parallelism for long context.

    python examples/bert_pretrain_toy.py --steps 30
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/bert_pretrain_toy.py --ring-sp 8 --seq-len 512
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models.bert import (BERTModel, BERTForPretrain,
                                             BERTPretrainLoss)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--units", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--masked", type=int, default=16)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--ring-sp", type=int, default=0,
                   help="ring-attention sequence-parallel degree")
    args = p.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    ring = None
    if args.ring_sp:
        from incubator_mxnet_tpu.parallel import make_mesh
        ring = (make_mesh({"sp": args.ring_sp}), "sp")

    bert = BERTModel(num_layers=args.layers, units=args.units,
                     hidden_size=args.units * 4, num_heads=args.heads,
                     max_length=args.seq_len, vocab_size=args.vocab,
                     dropout=0.1, use_pooler=True, ring=ring)
    model = BERTForPretrain(bert, vocab_size=args.vocab)
    model.initialize(init=mx.init.Normal(0.02))
    loss_fn = BERTPretrainLoss()
    trainer = gluon.Trainer(
        model.collect_params(), "adamw",
        {"learning_rate": 1e-3, "wd": 0.01,
         "lr_scheduler": mx.optimizer.lr_scheduler.CosineScheduler(
             args.steps, base_lr=1e-3,
             warmup_steps=max(1, args.steps // 10))})

    B, T, M = args.batch_size, args.seq_len, args.masked
    for step in range(args.steps):
        ids = nd.array(rng.randint(0, args.vocab, (B, T)))
        types = nd.zeros((B, T))
        # ring attention shards full sequences; a valid_length mask is a
        # dense-attention feature (the model raises if both are given)
        vlen = None if ring else nd.array(np.full(B, T, np.int32))
        pos = nd.array(np.stack([rng.choice(T, M, replace=False)
                                 for _ in range(B)]))
        mlm_label = nd.array(rng.randint(0, args.vocab, (B, M)))
        nsp_label = nd.array(rng.randint(0, 2, B))
        with autograd.record():
            mlm, nsp = model(ids, types, vlen, pos)
            loss = loss_fn(mlm, nsp, mlm_label, nsp_label)
        loss.backward()
        trainer.step(B)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss.asnumpy().mean()):.4f}")


if __name__ == "__main__":
    main()
