"""Int8 quantized inference (reference workflow:
example/quantization/imagenet_gen_qsym.py + contrib.quantization).

Train LeNet briefly on synthetic MNIST-shaped data, calibrate + quantize
it to int8 (symmetric, per-channel weight scales — the MXU-native form),
and compare fp32 vs int8 predictions and latency shape.

Run:  python examples/quantize_inference.py          (TPU if available)
      PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/quantize_inference.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.contrib import quantization as q


def main():
    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, in_channels=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, in_channels=6, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(init=mx.init.Xavier())

    rng = np.random.RandomState(0)
    data = rng.rand(512, 1, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, 512)
    net(nd.array(data[:1]))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(2):
        tot = 0.0
        for i in range(0, 512, 64):
            with mx.autograd.record():
                loss = L(net(nd.array(data[i:i + 64])),
                         nd.array(labels[i:i + 64]))
            loss.backward()
            trainer.step(64)
            tot += float(loss.mean().asnumpy())
        print(f"epoch {epoch}: loss {tot / 8:.4f}")

    fp32_pred = net(nd.array(data)).asnumpy().argmax(1)

    # calibrate on a held-out slice, quantize in place
    calib = [nd.array(data[i:i + 64]) for i in range(0, 256, 64)]
    qnet = q.quantize_net(net, calib_data=calib)
    int8_pred = qnet(nd.array(data)).asnumpy().argmax(1)
    agree = (int8_pred == fp32_pred).mean()
    print(f"int8 vs fp32 top-1 agreement: {agree:.1%}")

    x = nd.array(data[:64])
    for name, f in (("int8", qnet),):
        f(x).asnumpy()                      # warm
        t0 = time.time()
        for _ in range(10):
            out = f(x)
        np.asarray(out.asnumpy()[:1])       # host fetch = barrier
        print(f"{name}: {64 * 10 / (time.time() - t0):.0f} img/s")
    assert agree >= 0.98


if __name__ == "__main__":
    main()
