"""Classic symbol-API RNN training: mx.rnn cells + BucketingModule +
BucketSentenceIter (the reference's example/rnn/bucketing workflow,
rebuilt TPU-first: each bucket length compiles once to its own XLA
executable; weights are shared across buckets via shared_module).

Toy task: next-token prediction on a synthetic integer language with
variable-length sentences.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, rnn
from incubator_mxnet_tpu import symbol as sym


def make_sentences(n, vocab, rng):
    """Deterministic grammar: token_{t+1} = (token_t*3 + 1) % (vocab-1) + 1
    (tokens stay in [1, vocab-1]; 0 is the pad/ignore label), lengths
    4..12 — learnable by a small LSTM."""
    out = []
    for _ in range(n):
        ln = rng.randint(4, 13)
        s = [rng.randint(1, vocab)]
        for _ in range(ln - 1):
            s.append((s[-1] * 3 + 1) % (vocab - 1) + 1)  # stays in [1, V-1]
        out.append(s)
    return out


def sym_gen_factory(vocab, embed, hidden, layers, batch_size):
    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        emb = sym.Embedding(data=data, input_dim=vocab, output_dim=embed,
                            name="embed")
        stack = rnn.SequentialRNNCell()
        for i in range(layers):
            stack.add(rnn.LSTMCell(hidden, prefix=f"lstm_l{i}_"))
        outputs, _ = stack.unroll(seq_len, emb,
                                  stack.begin_state(batch_size=batch_size),
                                  layout="NTC", merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label_flat = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, label_flat, use_ignore=True,
                                ignore_label=0, name="softmax")
        return out, ("data",), ("softmax_label",)
    return sym_gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-sentences", type=int, default=2000)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--embed", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    sentences = make_sentences(args.num_sentences, args.vocab, rng)
    buckets = [6, 9, 12]
    train = io.BucketSentenceIter(sentences, args.batch_size,
                                  buckets=buckets, invalid_label=0,
                                  label_name="softmax_label")

    mod = mx.mod.BucketingModule(
        sym_gen_factory(args.vocab, args.embed, args.hidden, args.layers,
                        args.batch_size),
        default_bucket_key=train.default_bucket_key)
    metric = mx.metric.Perplexity(ignore_label=0)
    mod.fit(train, num_epoch=args.epochs, eval_metric=metric,
            optimizer="adam", optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier())

    # report final train perplexity
    metric.reset()
    train.reset()
    for batch in train:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    name, ppl = metric.get()
    print(f"final {name}={ppl:.3f}")
    # the deterministic grammar is fully predictable: perplexity must
    # approach 1; anything < 2 proves the model learned the transition
    assert ppl < 2.0, f"perplexity too high: {ppl}"


if __name__ == "__main__":
    main()
