"""Gluon MNIST training (the reference's image-classification starter,
example/gluon/mnist). Runs on the real TPU chip when the backend is up;
`JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=` runs it anywhere.

    python examples/train_mnist_gluon.py --epochs 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models import get_model


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--num-examples", type=int, default=4096)
    args = p.parse_args()

    mx.random.seed(0)
    # MNISTIter falls back to a deterministic synthetic set when the idx
    # files are absent (zero-egress pods)
    train = mx.io.MNISTIter(batch_size=args.batch_size, flat=False,
                            num_examples=args.num_examples)

    net = get_model("lenet", classes=10, layout="NCHW")
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        tic = time.time()
        n = 0
        for batch in train:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update(y, out)
            n += args.batch_size
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.4f} "
              f"({n / (time.time() - tic):.0f} img/s)")

    net.save_parameters("/tmp/lenet_mnist.params")
    print("saved /tmp/lenet_mnist.params")


if __name__ == "__main__":
    main()
