#!/usr/bin/env python
"""Train a small causal language model and generate text.

Mirrors the reference's language-model example flow (GluonNLP
word_language_model): build a vocabulary with contrib.text, batch a
corpus into fixed windows, train TransformerLM with the shifted-CE
loss, then sample continuations with the KV-cache decoder.

Run (CPU or TPU):  python examples/train_lm.py [--steps 200]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.contrib import text  # noqa: E402
from incubator_mxnet_tpu.models import TransformerLM, lm_loss  # noqa: E402

TOY_CORPUS = """
the quick brown fox jumps over the lazy dog
the lazy dog sleeps while the quick fox runs
a quick fox and a lazy dog share the yard
""" * 32


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    vocab = text.Vocabulary(text.count_tokens_from_str(TOY_CORPUS))
    tokens = np.array(vocab.to_indices(TOY_CORPUS.split()), np.float32)
    n_win = (len(tokens) - 1) // args.seq_len
    windows = np.stack([tokens[i * args.seq_len:(i + 1) * args.seq_len]
                        for i in range(n_win)])
    print(f"vocab {len(vocab)} tokens, {n_win} windows of {args.seq_len}")

    model = TransformerLM(len(vocab), num_layers=2, units=128,
                          hidden_size=256, num_heads=4,
                          max_length=2 * args.seq_len)
    model.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    rng = np.random.RandomState(0)
    t0 = time.time()
    for step in range(args.steps):
        batch = nd.array(windows[rng.randint(0, n_win, args.batch)])
        with mx.autograd.record():
            loss = lm_loss(model(batch), batch)
        loss.backward()
        trainer.step(args.batch)
        if step % 50 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss.mean().asnumpy()):.4f} "
                  f"({time.time() - t0:.1f}s)")

    prompt = "the quick brown".split()
    ids = np.array([vocab.to_indices(prompt)], np.float32)
    out = model.generate(ids, 8).asnumpy()[0].astype(int)
    print("greedy :", " ".join(vocab.to_tokens([int(i) for i in out])))
    out = model.generate(ids, 8, temperature=0.8, seed=1).asnumpy()[0]
    print("sampled:", " ".join(vocab.to_tokens([int(i) for i in out])))


if __name__ == "__main__":
    main()
