#!/usr/bin/env python
"""Distributed causal-LM training with sharded checkpoint/resume.

The flagship training loop end-to-end: TransformerLM on a data-parallel
mesh via FusedTrainStep (fwd+bwd+psum+AdamW as ONE XLA program, ZeRO-1
optimizer-state sharding), periodic sharded checkpoints, and resume —
rerunning the script continues from the latest checkpoint bit-exactly.

Run (CPU demo):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_lm_distributed.py --steps 40
On TPU hardware drop the env vars; on a pod, add mx.distributed.init()
(tools/launch.py) and the same mesh spans all hosts.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.models import TransformerLM  # noqa: E402
from incubator_mxnet_tpu.models.transformer_lm import lm_loss  # noqa: E402
from incubator_mxnet_tpu.parallel import (FusedTrainStep, latest_step,  # noqa: E402
                                          make_mesh, restore_train_step,
                                          save_train_step)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--save-every", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--ckpt-dir", default="/tmp/lm_ckpt_demo")
    args = ap.parse_args()

    import jax
    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev}) if n_dev > 1 else None
    print(f"devices: {n_dev} ({'dp mesh' if mesh else 'single'})")

    mx.random.seed(0)
    np.random.seed(0)
    model = TransformerLM(vocab_size=64, num_layers=2, units=128,
                          hidden_size=256, num_heads=4,
                          max_length=args.seq_len)
    model.initialize(init=mx.init.Xavier())
    step = FusedTrainStep(model, lambda logits, y: lm_loss(logits, y).mean(),
                          mx.optimizer.create("adamw", learning_rate=3e-3),
                          mesh=mesh, shard_optimizer_states=mesh is not None)

    def batch(i):
        # deterministic per-step data: resume sees the SAME stream the
        # uninterrupted run would, so continuation is bit-exact
        rng = np.random.RandomState(1000 + i)
        starts = rng.randint(0, 8, args.batch)
        seq = (starts[:, None] + np.arange(args.seq_len)[None, :]) % 8
        return nd.array(seq.astype(np.float32))

    x0 = batch(-1)
    t0 = time.time()
    float(step(x0, x0))                                   # compile
    print(f"compiled in {time.time() - t0:.1f}s")

    # checkpoints are numbered by SCRIPT step (explicit step_num=), not
    # by step._num_update, which also counts the compile call above
    start = latest_step(args.ckpt_dir) or 0
    if start:
        restore_train_step(args.ckpt_dir, step, step_num=start)
        print(f"resumed from step {start}")

    for i in range(start, args.steps):
        xb = batch(i)
        loss = float(step(xb, xb))
        if (i + 1) % args.save_every == 0 or i + 1 == args.steps:
            path = save_train_step(args.ckpt_dir, step, step_num=i + 1)
            print(f"step {i + 1}: loss {loss:.4f} (checkpoint -> {path})")
        elif (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss {loss:.4f}")
    print("done; rerun to resume from the latest checkpoint")


if __name__ == "__main__":
    main()
