#!/usr/bin/env python
"""Train LeNet on synthetic MNIST with the Estimator API.

The reference's estimator flow (gluon.contrib.estimator): the train loop
as a library, with validation, logging, checkpointing, and early
stopping as composable event handlers.

Run:  python examples/estimator_mnist.py [--epochs 3]
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, metric, nd  # noqa: E402
from incubator_mxnet_tpu.gluon.contrib.estimator import (  # noqa: E402
    CheckpointHandler, EarlyStoppingHandler, Estimator)
from incubator_mxnet_tpu.models import get_model  # noqa: E402


def synthetic_mnist(n, seed):
    """Class-conditional blobs rendered as 28x28 images — learnable fast,
    no downloads."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.25
    for i, cls in enumerate(y):
        r, c = divmod(int(cls), 4)
        x[i, 0, 4 + r * 7:10 + r * 7, 2 + c * 6:8 + c * 6] += 0.75
    return x, y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--ckpt-dir", default="/tmp/estimator_mnist_ckpt")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    xt, yt = synthetic_mnist(args.num_examples, 0)
    xv, yv = synthetic_mnist(args.num_examples // 4, 1)
    train = gluon.data.DataLoader(
        gluon.data.ArrayDataset(nd.array(xt), nd.array(yt)),
        batch_size=args.batch_size, shuffle=True)
    val = gluon.data.DataLoader(
        gluon.data.ArrayDataset(nd.array(xv), nd.array(yv)),
        batch_size=args.batch_size)

    net = get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()

    est = Estimator(
        net=net,
        loss=gluon.loss.SoftmaxCrossEntropyLoss(),
        train_metrics=metric.Accuracy(),
        val_metrics=metric.Accuracy(),
        trainer=gluon.Trainer(net.collect_params(), "adam",
                              {"learning_rate": 1e-3}))
    est.fit(train_data=train, val_data=val, epochs=args.epochs,
            event_handlers=[
                CheckpointHandler(args.ckpt_dir, model_prefix="lenet",
                                  monitor=est.val_metrics[0],
                                  save_best=True),
                EarlyStoppingHandler(monitor=est.val_metrics[0],
                                     patience=2, mode="max")])

    val_acc = dict(m.get_name_value()[0] for m in est.val_metrics)
    print(f"final validation accuracy={val_acc['accuracy']:.4f}")
    print("best checkpoint:",
          os.path.join(args.ckpt_dir, "lenet-best.params"))


if __name__ == "__main__":
    main()
