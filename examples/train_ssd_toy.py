"""Toy SSD detection training: synthetic record file -> ImageDetIter with
box-aware augmentation -> SSD targets/loss -> detect() with NMS.

    python examples/train_ssd_toy.py --epochs 3
"""
import argparse
import io as _io
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, image, nd, recordio
from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss


def make_dataset(path, n=24, seed=0):
    from PIL import Image as PILImage
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(image.idx_path_for(path), path, "w")
    for i in range(n):
        img = np.zeros((64, 64, 3), np.uint8)
        cls = i % 2
        x0, y0 = rng.uniform(0.1, 0.4, 2)
        x1, y1 = x0 + 0.4, y0 + 0.4
        img[int(y0 * 64):int(y1 * 64), int(x0 * 64):int(x1 * 64), cls] = 255
        buf = _io.BytesIO()
        PILImage.fromarray(img).save(buf, format="PNG")
        header = recordio.IRHeader(0, [2, 5, float(cls), x0, y0, x1, y1],
                                   i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=4)
    args = p.parse_args()

    mx.random.seed(0)
    rec_path = os.path.join(tempfile.mkdtemp(), "toy_det.rec")
    make_dataset(rec_path)
    it = image.ImageDetIter(batch_size=args.batch_size,
                            data_shape=(3, 32, 32), path_imgrec=rec_path,
                            rand_mirror=True)

    backbone = gluon.nn.HybridSequential()
    backbone.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                 activation="relu"))
    net = SSD(backbone, num_classes=2, sizes=[[0.3, 0.5], [0.6, 0.8]],
              ratios=[[1, 2]] * 2, extra_channels=(32,), layout="NCHW")
    net.initialize(init=mx.init.Xavier())
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9,
                             "clip_gradient": 10.0})

    for epoch in range(args.epochs):
        it.reset()
        losses = []
        for batch in it:
            with autograd.record():
                anchor, cls_pred, box_pred = net(batch.data[0])
                with autograd.pause():
                    bt, bm, ct = net.targets(anchor, cls_pred,
                                             batch.label[0])
                loss = loss_fn(cls_pred, box_pred, ct, bt, bm)
            loss.backward()
            trainer.step(args.batch_size)
            losses.append(float(loss.asnumpy().mean()))
        print(f"epoch {epoch}: loss {np.mean(losses):.3f}")

    it.reset()
    batch = next(iter(it))
    det = net.detect(batch.data[0], threshold=0.05)  # toy-training scores
    kept = (det.asnumpy()[:, :, 0] >= 0).sum()
    print(f"detect(): {kept} boxes above threshold after NMS")


if __name__ == "__main__":
    main()
