"""ONNX interchange workflow (reference:
example/onnx/ + python/mxnet/contrib/onnx docs).

Train a small CNN, trace it to a symbol graph, export to ONNX, import it
back, and check the round trip preserves predictions. The emitted file is
wire-compatible with stock onnxruntime (the schema bindings mirror the
public onnx.proto3 field numbers), so the same file serves CPU/GPU
serving stacks outside this framework.

    JAX_PLATFORMS=cpu python examples/onnx_export_import.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet
from incubator_mxnet_tpu.gluon.symbolize import trace_symbol


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--out", default="/tmp/mxtpu_model.onnx")
    args = p.parse_args()

    rng = np.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, layout="NCHW"),
            gluon.nn.BatchNorm(axis=1), gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2, layout="NCHW"),
            gluon.nn.Conv2D(32, 3, padding=1, layout="NCHW"),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(layout="NCHW"),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    x = nd.array(rng.rand(32, 3, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, 32).astype(np.float32))
    for step in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(32)
    print(f"trained {args.steps} steps, final loss {float(loss):.4f}")

    # 1) gluon -> symbol graph (+ params split into args/auxs)
    sym, arg_params, aux_params = trace_symbol(net)
    print(f"traced: {len(sym.tojson())} bytes of symbol JSON, "
          f"{len(arg_params)} args, {len(aux_params)} auxs")

    # 2) symbol -> ONNX file
    onnx_mxnet.export_model(sym, {**arg_params, **aux_params},
                            [(1, 3, 28, 28)], onnx_file_path=args.out)
    meta = onnx_mxnet.get_model_metadata(args.out)
    print(f"exported {args.out} ({os.path.getsize(args.out)} bytes); "
          f"inputs={meta['input_tensor_data']}")

    # 3) ONNX -> symbol + params, and prediction parity
    sym2, arg2, aux2 = onnx_mxnet.import_model(args.out)
    x1 = nd.array(rng.rand(1, 3, 28, 28).astype(np.float32))
    y_ref = net(x1).asnumpy()
    ex = sym2.bind(args={"data": x1, **arg2}, aux_states=aux2)
    y_imp = ex.forward(is_train=False)[0].asnumpy()
    err = float(np.abs(y_ref - y_imp).max())
    print(f"round-trip max abs diff: {err:.2e}")
    assert err < 1e-4
    print("OK: ONNX round trip preserves predictions")

    # 4) the transformer family exports too (attention decomposes to
    #    opset-13 primitives; the causal mask rides as a constant)
    from incubator_mxnet_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=64, num_layers=2, units=64,
                       hidden_size=128, num_heads=4, max_length=32)
    lm.initialize(init=mx.init.Xavier())
    ids = nd.array(rng.randint(0, 64, (2, 12)).astype(np.float32))
    lm_ref = lm(ids).asnumpy()
    lsym, larg, laux = trace_symbol(lm, "data")
    lm_path = args.out.replace(".onnx", "") + "_lm.onnx"
    onnx_mxnet.export_model(lsym, {**larg, **laux}, [(2, 12)],
                            onnx_file_path=lm_path)
    ls2, la2, lx2 = onnx_mxnet.import_model(lm_path)
    lm_out = ls2.bind(args={"data": ids, **la2},
                      aux_states=lx2).forward(is_train=False)[0].asnumpy()
    lm_err = float(np.abs(lm_ref - lm_out).max())
    print(f"causal-LM ONNX round-trip max abs diff: {lm_err:.2e}")
    assert lm_err < 1e-4
    print("OK: transformer ONNX export verified")


if __name__ == "__main__":
    main()
