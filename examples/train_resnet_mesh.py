"""ResNet-50 synthetic-ImageNet training on a device mesh — the fused
train-step performance path (forward + backward + gradient collective +
optimizer in ONE XLA computation, dp-axis all-reduce riding ICI).

Single chip:
    python examples/train_resnet_mesh.py --steps 10
8 virtual CPU devices (no TPU needed):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_resnet_mesh.py --dp 8 --batch-size 32 --size 64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.models import get_model
from incubator_mxnet_tpu.parallel import FusedTrainStep, make_mesh


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50_v1")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--size", type=int, default=224)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (0 = single device)")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()

    mx.random.seed(0)
    np.random.seed(0)
    net = get_model(args.model, classes=1000, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4,
                              multi_precision=(args.dtype == "bfloat16"))
    mesh = make_mesh({"dp": args.dp}) if args.dp else None
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), opt,
                          mesh=mesh)

    x = nd.array(np.random.randn(args.batch_size, args.size, args.size, 3)
                 .astype(np.float32))
    if args.dtype == "bfloat16":
        x = x.astype("bfloat16")
    y = nd.array(np.random.randint(0, 1000, args.batch_size))

    print("compiling fused step...")
    loss = float(step(x, y))            # compile + warmup
    t0 = time.time()
    out = None
    for _ in range(args.steps):
        out = step(x, y)
    # host fetch = the only true barrier
    final = float(out) if out is not None else loss
    dt = max(time.time() - t0, 1e-9)
    print(f"{args.batch_size * args.steps / dt:.1f} img/s "
          f"(loss {loss:.3f} -> {final:.3f}, mesh={mesh})")


if __name__ == "__main__":
    main()
