#!/bin/bash
# Tier-1 ingest-pipeline smoke: lenet ON CPU through the whole-loop
# executor TWICE with an injected 700 ms/batch decode cost
# (BENCH_IO_SLOW_MS — a sleep in the decode pool's transform hook),
# then assert the pipelining claim from the two BENCH jsons:
#   serial    — io_workers=1, depth=1: decode wall lands on the
#               consumer's critical path, io.wait_ms is large and the
#               devicescope window shows input starvation whose split
#               is decode-dominated (mxdiag io must render the
#               "raise io_workers" triage line from it);
#   pipelined — io_workers=4, depth=2: the pool hides the same decode
#               cost behind compute, so io.wait_ms drops, throughput
#               rises, and the measured overlap inequality holds:
#               the pipelined run's whole steady WALL is smaller than
#               the serial run's cumulative decode+put attribution
#               (stages truly overlapped — they did not just move).
#   both runs — extra.io validates under trace_check (schema +
#               counter families), mxdiag io renders, and
#               perf_regress.py accepts the pair (the knob diff must
#               surface as context, not break the comparison).
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT_SER=${1:-/tmp/mxtpu_io_smoke_serial.json}
OUT_PIPE=/tmp/mxtpu_io_smoke_pipelined.json
LOG=/tmp/mxtpu_io_smoke.log
: > "$LOG"

run_bench() {  # $1 = io_workers, $2 = prefetch depth, $3 = out json
  JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=24 \
    BENCH_DTYPE=float32 BENCH_LOOP_CHUNK=4 BENCH_K1_CONTROL=0 \
    BENCH_PREFLIGHT=0 BENCH_TRACE=0 BENCH_DEVICESCOPE=1 \
    BENCH_IO_SLOW_MS=700 \
    BENCH_IO_WORKERS="$1" BENCH_PREFETCH_DEPTH="$2" \
    timeout -k 10 900 python bench.py > "$3" 2>> "$LOG"
}

echo "io_smoke: serial run (io_workers=1, depth=1, slow decode 700 ms)"
run_bench 1 1 "$OUT_SER"
rc=$?
if [ "$rc" != "0" ]; then
  echo "io_smoke: serial bench failed rc=$rc"; tail -30 "$LOG"; exit 1
fi

echo "io_smoke: pipelined run (io_workers=4, depth=2, same decode)"
run_bench 4 2 "$OUT_PIPE"
rc=$?
if [ "$rc" != "0" ]; then
  echo "io_smoke: pipelined bench failed rc=$rc"; tail -30 "$LOG"; exit 1
fi

python - "$OUT_SER" "$OUT_PIPE" <<'EOF' || exit 1
import json, sys
ser = json.load(open(sys.argv[1]))
pipe = json.load(open(sys.argv[2]))
for tag, doc in (("serial", ser), ("pipelined", pipe)):
    if doc.get("error"):
        sys.exit(f"{tag} bench reported error: {doc['error']}")
    io = (doc.get("extra") or {}).get("io")
    assert isinstance(io, dict), f"{tag}: no extra.io section"
s_io = ser["extra"]["io"]; p_io = pipe["extra"]["io"]
assert s_io["workers"] == 1 and s_io["depth"] == 1, s_io
assert p_io["workers"] == 4 and p_io["depth"] == 2, p_io
assert s_io["slow_ms"] == 700.0 and p_io["slow_ms"] == 700.0, \
    "injected decode cost missing from extra.io"
# the decode pool must CUT the consumer's empty-buffer wait: with one
# worker the 4x700 ms chunk decode serializes in front of every pop;
# with four it overlaps compute. 0.6 leaves CI-box noise headroom.
assert p_io["wait_ms"] < 0.6 * s_io["wait_ms"], \
    (f"pipelining did not cut the consumer wait: serial "
     f"{s_io['wait_ms']:.0f} ms vs pipelined {p_io['wait_ms']:.0f} ms")
# measured overlap inequality: the pipelined steady WALL must be
# smaller than the serial run's decode+put attribution — overlapped
# work, not relocated work. Walls derive from the headline throughput.
def wall_ms(doc):
    ex = doc["extra"]
    return ex["batch"] * ex["steps"] / doc["value"] * 1e3
assert wall_ms(pipe) < s_io["decode_ms"] + s_io["put_ms"], \
    (f"no overlap win: pipelined wall {wall_ms(pipe):.0f} ms vs serial "
     f"decode+put {s_io['decode_ms'] + s_io['put_ms']:.0f} ms")
# and the headline: same model, same injected cost, higher throughput
assert pipe["value"] > ser["value"], \
    f"pipelined {pipe['value']} <= serial {ser['value']} samples/s"
# devicescope attribution: the serial run starves on decode, and the
# split must say so (the signal autotune's prune_plan promotes
# io_workers on)
ds = (ser.get("extra") or {}).get("devicescope") or {}
split = (ds.get("gaps") or {}).get("input_starved_split")
assert isinstance(split, dict), "serial run has no input_starved_split"
assert split.get("dominant") == "decode", \
    f"serial starvation not decode-dominated: {split}"
# busy fraction: the pipelined chip does proportionally more work
sb = ds.get("busy_fraction")
pb = ((pipe.get("extra") or {}).get("devicescope") or {}).get(
    "busy_fraction")
assert sb is not None and pb is not None, "busy_fraction missing"
assert pb > sb, f"pipelined busy {pb} <= serial busy {sb}"
print(f"io_smoke: OK (serial {ser['value']} -> pipelined "
      f"{pipe['value']} samples/s; wait {s_io['wait_ms']:.0f} -> "
      f"{p_io['wait_ms']:.0f} ms; serial starve split {split})")
EOF

# schema-check both BENCH jsons (extra.io + counter families)
python tools/trace_check.py "$OUT_SER" "$OUT_PIPE" || exit 1

# the renderer must handle both shapes, and the serial run's triage
# line must point at the decode pool, not at prefetch depth
python tools/mxdiag.py io "$OUT_PIPE" > /dev/null \
  || { echo "io_smoke: mxdiag io failed on pipelined run"; exit 1; }
IODIAG=$(python tools/mxdiag.py io "$OUT_SER") \
  || { echo "io_smoke: mxdiag io failed on serial run"; exit 1; }
echo "$IODIAG" | grep -q "raise io_workers" \
  || { echo "io_smoke: serial triage line missing 'raise io_workers':";
       echo "$IODIAG"; exit 1; }

# perf_regress must accept the pair; the io_workers diff is CONTEXT
REGOUT=$(python tools/perf_regress.py --threshold 0.9 \
           --busy-threshold 0.9 "$OUT_PIPE" "$OUT_SER" 2>&1)
rc=$?
if [ "$rc" != "0" ]; then
  # serial IS slower — a flagged regression is acceptable, a crash or
  # schema rejection is not
  echo "$REGOUT" | grep -qi "regress" \
    || { echo "io_smoke: perf_regress rejected the pair:";
         echo "$REGOUT"; exit 1; }
fi

echo "io_smoke: OK"
