#!/usr/bin/env python
"""Closed-loop serving load harness: ramp concurrency, find the knee.

The serving-path counterpart of ``bench.py``'s training sweep (ROADMAP
item 1's closing gate): freeze a model, start :class:`ModelServer`, and
drive K concurrent **closed-loop** clients (each fires its next request
the moment the previous response lands — the load model under which
"QPS at a p99 target" is well-defined) through a ramped concurrency
sweep. For every level the harness records client-observed QPS and
p50/p95/p99, then finds the **saturation knee** — the last level where
throughput still scales before p99 inflects — and emits one
trace_check-valid BENCH json:

* ``metric`` = ``serve_load_<model>_qps_at_knee``, ``value`` = the QPS
  at the knee (gated by ``tools/perf_regress.py``'s value gate);
* ``extra.serving`` — the standard serving section (schema enforced by
  ``check_bench_json``), with p50/p95/p99 and qps measured AT the knee
  level and the request/batch accounting + latency histogram from the
  server's cumulative registry snapshot;
* ``extra.serve_load`` — the full per-level sweep table plus the knee
  verdict (``knee_concurrency`` / ``qps_at_knee`` / ``p99_at_knee_ms``,
  gated by perf_regress's p99 gate);
* ``extra.servescope`` — the tail-latency attribution
  (``queue_wait + coalesce_delay + pad_overhead + device_exec +
  respond`` per bucket, with roofline + resharding verdicts attached —
  ``check_servescope_extra`` validates it, ``mxdiag.py serve`` renders
  it);
* ``extra.fleetscope`` — cross-process trace accounting: every client
  request carries a freshly minted W3C ``traceparent`` header, and the
  section reports how many traces the serving side actually joined
  (``client_minted`` / ``sampled`` / ``joined`` / ``join_rate``, with
  ``unjoined_forwards`` counted — never guessed away). In --fleet mode
  it adds the **wire-gap** percentiles (router-observed forward time
  minus replica-observed total: a difference of durations, so clock
  skew cannot enter it), per-replica trace p99s, and the
  ``replica_spread`` straggler ratio — ``check_fleetscope_extra``
  validates it, ``mxdiag.py trace``/``pod`` render the raw records.

A server that dies mid-sweep (every request of a level failing, or a
dead /healthz) produces a self-describing ``{"status": "env_failure"}``
artifact — the bench.py convention perf_regress skips — instead of a
zero that would poison the BENCH trajectory.

With ``--fleet N`` the harness drives a whole replica fleet instead of
one server: N **spawned worker processes** (each its own GIL, warmed
through the shared on-disk
:class:`~incubator_mxnet_tpu.fleet.CompileCache`) behind a
:class:`~incubator_mxnet_tpu.fleet.Router`, the load aimed at the
router's front door. The artifact gains ``extra.fleet`` — per-replica
client-observed QPS/p99 (keyed off the ``replica`` tag the router
stamps into every reply), the dispatch-imbalance ratio, and the
router/cache accounting — validated by ``check_fleet_extra`` and
rendered by ``mxdiag.py fleet``; ``extra.serving`` is the MERGE of the
workers' ``/stats`` exports (each process owns a registry). The metric
name grows a ``_fleetN`` suffix so perf_regress's both-sides contract
compares fleet runs against fleet baselines, never against the
single-server trajectory. Replica scaling is a multi-core claim: on a
1-core host the fleet only measures its own routing overhead.

In --fleet mode each worker is spawned with ``servescope``/
``fleetscope``/``export`` armed and its own ``mxtpu.events/2`` log
(``<events>_replica_<pid>.jsonl``); the router's ``fleetscope.request``
records land in the harness's events file, and after the sweep the two
sides are joined on ``trace_id`` (one request = ONE trace: router admit
→ wire → replica queue_wait → coalesce → device_exec → respond). A
:class:`~incubator_mxnet_tpu.fleetscope.Collector` polls every
replica's ``diagnostics.export`` endpoint during the sweep; its
clock-offset snapshot rides along under ``extra.fleetscope.collector``.

Usage:
    python tools/serve_load.py [--model lenet] [--ramp 4,8,16,32,64]
        [--level-requests 128] [--max-delay-ms 5] [--out BENCH.json]
        [--events EVENTS.jsonl] [--sample N] [--devicescope N]
        [--fleet N] [--fleet-cache DIR]

Pure helpers (:func:`find_knee`, :func:`run_level`, :func:`sweep`,
:func:`write_env_failure`) are importable without a backend —
``tests/test_servescope.py`` unit-tests knee detection and the
env-failure path against synthetic levels.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

__all__ = ["find_knee", "run_level", "sweep", "build_result",
           "merge_serving_stats", "write_env_failure", "ServerDied",
           "read_event_records", "build_fleetscope_extra",
           "main", "DEFAULT_RAMP", "KNEE_QPS_GAIN", "KNEE_P99_MULT"]

DEFAULT_RAMP = "4,8,16,32,64"
# knee rules: saturation begins at the first level whose marginal QPS
# gain is below KNEE_QPS_GAIN x the concurrency scaling, or whose p99
# exceeds KNEE_P99_MULT x the base level's p99 (the inflection)
KNEE_QPS_GAIN = 0.10
KNEE_P99_MULT = 3.0


class ServerDied(RuntimeError):
    """Every request of a level failed (or /healthz went away): the
    server is gone, and the sweep has no perf meaning."""


# ---------------------------------------------------------------------------
# knee detection (pure)
# ---------------------------------------------------------------------------

def find_knee(levels, qps_gain: float = KNEE_QPS_GAIN,
              p99_mult: float = KNEE_P99_MULT):
    """The saturation knee of a ramped sweep.

    ``levels``: dicts with ``concurrency``, ``qps``, ``p99_ms``,
    ordered by ascending concurrency. Returns ``(index, reason)`` of
    the knee level — the last level BEFORE saturation:

    * level i saturates on **throughput** when its relative QPS gain
      over level i-1 falls below ``qps_gain`` x the relative
      concurrency increase (doubling clients for <10% more QPS means
      the extra clients only queue);
    * level i saturates on **latency** when ``p99_ms`` exceeds
      ``p99_mult`` x the base level's p99 (the inflection — latency has
      replaced throughput as the thing that grows).

    With no saturation observed the knee is the last level (reason
    says so: the ramp didn't reach the knee)."""
    if not levels:
        raise ValueError("find_knee needs at least one level")
    base_p99 = levels[0].get("p99_ms") or 0.0
    for i in range(1, len(levels)):
        prev, cur = levels[i - 1], levels[i]
        scale = (cur["concurrency"] / prev["concurrency"]) - 1.0
        gain = ((cur["qps"] - prev["qps"]) / prev["qps"]
                if prev["qps"] > 0 else 0.0)
        if scale > 0 and gain < qps_gain * scale:
            return i - 1, (f"throughput saturated at concurrency "
                           f"{cur['concurrency']} (+{gain:.1%} QPS for "
                           f"+{scale:.0%} clients)")
        if base_p99 > 0 and (cur.get("p99_ms") or 0.0) \
                > p99_mult * base_p99:
            return i - 1, (f"p99 inflected at concurrency "
                           f"{cur['concurrency']} "
                           f"({cur['p99_ms']:.1f} ms > {p99_mult:g}x "
                           f"base {base_p99:.1f} ms)")
    return len(levels) - 1, "no saturation observed (ramp too short?)"


# ---------------------------------------------------------------------------
# closed-loop level runner
# ---------------------------------------------------------------------------

def _percentile(sorted_vals, q):
    import math
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, math.ceil(q * n) - 1))]


def run_level(send_fn, concurrency: int, total_requests: int) -> dict:
    """Drive ``total_requests`` through ``concurrency`` closed-loop
    client threads. ``send_fn(i)`` issues request i and blocks until
    its response (raising on failure). Returns the level dict
    {concurrency, requests, ok, errors, wall_s, qps, p50/p95/p99_ms};
    raises :class:`ServerDied` when NOTHING succeeded."""
    counter = [0]
    lock = threading.Lock()
    lats, errs = [], []

    def client():
        while True:
            with lock:
                i = counter[0]
                if i >= total_requests:
                    return
                counter[0] += 1
            t0 = time.perf_counter()
            try:
                send_fn(i)
            except Exception as e:  # noqa: BLE001 — a failed request is
                with lock:          # data, not a harness crash
                    errs.append(f"{type(e).__name__}: {e}")
                continue
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                lats.append(dt)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(max(1, int(concurrency)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if not lats:
        raise ServerDied(
            f"level concurrency={concurrency}: all {total_requests} "
            f"requests failed; first error: {errs[0] if errs else '?'}")
    lats.sort()
    return {
        "concurrency": int(concurrency),
        "requests": int(total_requests),
        "ok": len(lats),
        "errors": len(errs),
        "first_error": errs[0][:200] if errs else None,
        "wall_s": round(wall, 4),
        "qps": round(len(lats) / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lats, 0.50), 3),
        "p95_ms": round(_percentile(lats, 0.95), 3),
        "p99_ms": round(_percentile(lats, 0.99), 3),
        "mean_ms": round(sum(lats) / len(lats), 3),
    }


def sweep(send_fn, ramp, level_requests: int, log=print,
          before_level=None) -> list:
    """Run every ramp level through :func:`run_level` (closed loop,
    ascending concurrency). ``before_level(index, concurrency)``, when
    given, runs ahead of each level (main() arms the devicescope
    window over the most loaded one). Propagates :class:`ServerDied`."""
    levels = []
    for li, c in enumerate(ramp):
        if before_level is not None:
            before_level(li, c)
        lv = run_level(send_fn, c, level_requests)
        levels.append(lv)
        log(f"serve_load: concurrency {c:>4}  qps {lv['qps']:>9.1f}  "
            f"p50/p95/p99 {lv['p50_ms']:.1f}/{lv['p95_ms']:.1f}/"
            f"{lv['p99_ms']:.1f} ms  errors {lv['errors']}")
    return levels


# ---------------------------------------------------------------------------
# artifacts
# ---------------------------------------------------------------------------

def _hist_quantile(buckets, count, q):
    """Prometheus-style quantile estimate from a cumulative bucket dict
    (upper bound of the first bucket covering the target rank; the
    largest finite bound stands in for +Inf)."""
    target = q * count
    finite = sorted(((float(le), c) for le, c in buckets.items()
                     if le not in ("+Inf", "inf")), key=lambda x: x[0])
    for le, c in finite:
        if c >= target:
            return le
    return finite[-1][0] if finite else 0.0


def merge_serving_stats(snaps) -> dict:
    """Merge per-replica ModelServer ``/stats`` snapshots into one
    fleet-wide serving section (the --fleet path: spawned replicas
    each own a metrics registry, so the aggregate must be computed from
    their exported snapshots). Counters sum; the latency histograms —
    identical bucket bounds, same histogram family in every process —
    merge by summing cumulative counts per bound, with percentiles
    re-estimated from the merged buckets."""
    merged = {}
    hist = {"count": 0, "sum": 0.0, "buckets": {}}
    mins, maxs = [], []
    for s in snaps:
        for k, v in s.items():
            if k == "serving.latency_ms":
                continue
            if k.startswith("serving.") and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                merged[k] = merged.get(k, 0) + v
        h = s.get("serving.latency_ms")
        if isinstance(h, dict):
            hist["count"] += h.get("count", 0)
            hist["sum"] += h.get("sum", 0.0)
            if h.get("min") is not None:
                mins.append(h["min"])
            if h.get("max") is not None:
                maxs.append(h["max"])
            for le, c in (h.get("buckets") or {}).items():
                hist["buckets"][le] = hist["buckets"].get(le, 0) + c
    if mins:
        hist["min"] = min(mins)
    if maxs:
        hist["max"] = max(maxs)
    if hist["count"]:
        for key, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            hist[key] = _hist_quantile(hist["buckets"], hist["count"], q)
    if hist["buckets"]:
        merged["serving.latency_ms"] = hist
    batches = merged.get("serving.batches", 0)
    merged["batch_fill"] = (
        merged.get("serving.batched_requests", 0) / batches
        if batches else 0.0)
    return merged


def build_result(model_name: str, levels, knee_idx: int, reason: str,
                 server_stats: dict, servescope_extra=None,
                 devicescope_extra=None, meta=None) -> dict:
    """Assemble the BENCH json: value = QPS at the knee, the standard
    ``extra.serving`` section (percentiles AT the knee, accounting from
    the server's cumulative snapshot), the sweep table, and the
    attribution."""
    knee = levels[knee_idx]
    hist = server_stats.get("serving.latency_ms")
    serving = {
        "model": model_name,
        "clients": knee["concurrency"],
        "requests": int(server_stats.get("serving.requests", 0)),
        "responses": int(server_stats.get("serving.responses", 0)),
        "batches": int(server_stats.get("serving.batches", 0)),
        "batch_fill": round(float(server_stats.get("batch_fill", 0.0)), 3),
        "rejected_queue_full":
            int(server_stats.get("serving.rejected_queue_full", 0)),
        "rejected_deadline":
            int(server_stats.get("serving.rejected_deadline", 0)),
        "rejected_deadline_post_batch":
            int(server_stats.get("serving.rejected_deadline_post_batch",
                                 0)),
        "rejected_invalid":
            int(server_stats.get("serving.rejected_invalid", 0)),
        "slotted_admissions":
            int(server_stats.get("serving.slotted_admissions", 0)),
        "qps": knee["qps"],
        "p50_ms": knee["p50_ms"],
        "p95_ms": knee["p95_ms"],
        "p99_ms": knee["p99_ms"],
        "latency_ms": hist if isinstance(hist, dict) else None,
    }
    extra = {
        "model": f"serve_load_{model_name}",
        "batch": None,
        "dtype": "float32",
        "serving": serving,
        "serve_load": {
            "levels": levels,
            "knee_index": knee_idx,
            "knee_reason": reason,
            "knee_concurrency": knee["concurrency"],
            "qps_at_knee": knee["qps"],
            "p99_at_knee_ms": knee["p99_ms"],
        },
    }
    if servescope_extra is not None:
        extra["servescope"] = servescope_extra
    if devicescope_extra is not None:
        extra["devicescope"] = devicescope_extra
    if meta:
        extra.update(meta)
    return {
        "metric": f"serve_load_{model_name}_qps_at_knee",
        "value": knee["qps"],
        "unit": "requests/sec",
        "vs_baseline": None,
        "extra": extra,
    }


def read_event_records(path, name=None) -> list:
    """Every parsed record of an ``mxtpu.events`` JSONL file, optionally
    filtered by record ``name``. Unlike the collector's bounded live
    tail this reads the WHOLE file: the harness owns these files and
    they are sweep-sized. IO errors yield ``[]`` — post-run accounting,
    not truth."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(rec, dict) and (name is None
                                              or rec.get("name") == name):
                    out.append(rec)
    except OSError:
        pass
    return out


def build_fleetscope_extra(client_minted: int, router_records,
                           replica_records) -> dict:
    """Assemble the ``extra.fleetscope`` BENCH section from router-side
    ``fleetscope.request`` records and replica-side ``serving.request``
    records (the shape ``check_fleetscope_extra`` enforces).

    * ``sampled`` — router-observed SUCCESSFUL forwards (status 200):
      the join denominator;
    * ``joined`` — sampled traces whose replica-side span arrived;
      ``unjoined_forwards`` is the remainder, counted — never guessed;
    * ``wire_gap_ms`` — per joined trace, router ``forward_ms`` minus
      replica ``e2e_ms``. Both are perf_counter DURATIONS, so the
      difference is clock-skew free (docs/fleetscope.md);
    * ``per_replica`` / ``replica_spread`` — replica-observed trace p99
      per replica and max/median across them (the straggler signal the
      pod view renders)."""
    from incubator_mxnet_tpu.fleetscope import join_traces
    traces = join_traces(router_records, replica_records)
    sampled = joined = 0
    gaps, by_rep = [], {}
    for slot in traces.values():
        rtr = slot["router"]
        if rtr is None:
            continue
        rargs = rtr.get("args") or {}
        if rargs.get("status") != 200:
            continue
        sampled += 1
        rep = slot["replica"]
        if rep is None:
            continue
        joined += 1
        agg = by_rep.setdefault(slot["replica_name"] or "?",
                                {"n": 0, "e2e": [], "gaps": []})
        agg["n"] += 1
        pargs = rep.get("args") or {}
        e2e, fw = pargs.get("e2e_ms"), rargs.get("forward_ms")
        if isinstance(e2e, (int, float)):
            agg["e2e"].append(float(e2e))
            if isinstance(fw, (int, float)):
                gap = float(fw) - float(e2e)
                gaps.append(gap)
                agg["gaps"].append(gap)
    out = {
        "client_minted": int(client_minted),
        "sampled": sampled,
        "joined": joined,
        "unjoined_forwards": sampled - joined,
        "join_rate": round(joined / sampled, 6) if sampled else 0.0,
    }
    if gaps:
        gaps.sort()
        out["wire_gap_ms"] = {k: round(_percentile(gaps, q), 3)
                              for k, q in (("p50", 0.50), ("p95", 0.95),
                                           ("p99", 0.99))}
    rows, p99s = [], []
    for name in sorted(by_rep):
        agg = by_rep[name]
        row = {"name": name, "traces": agg["n"]}
        if agg["e2e"]:
            row["e2e_p99_ms"] = round(
                _percentile(sorted(agg["e2e"]), 0.99), 3)
            p99s.append(row["e2e_p99_ms"])
        if agg["gaps"]:
            row["wire_gap_p50_ms"] = round(
                _percentile(sorted(agg["gaps"]), 0.50), 3)
        rows.append(row)
    if rows:
        out["per_replica"] = rows
    if p99s:
        p99s.sort()
        # lower median: with 2 replicas the upper median IS the max and
        # the straggler ratio would pin at 1.0
        median = p99s[(len(p99s) - 1) // 2]
        if median > 0:
            out["replica_spread"] = round(p99s[-1] / median, 4)
    return out


def write_env_failure(path: str, metric: str, error: str) -> dict:
    """The self-describing environment-failure artifact (bench.py's
    preflight convention): perf_regress skips it, the trajectory stays
    unpoisoned, and the error travels with the file."""
    doc = {"status": "env_failure", "metric": metric, "value": 0.0,
           "unit": "requests/sec", "error": str(error)[:500],
           "ts": time.time()}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return doc


# ---------------------------------------------------------------------------
# main (backend-touching; imports deferred so helpers stay unit-testable)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="closed-loop serving load harness (ramped "
                    "concurrency, saturation knee, BENCH json)")
    ap.add_argument("--model", default=os.environ.get(
        "BENCH_SERVING_MODEL", "lenet"))
    ap.add_argument("--ramp", default=DEFAULT_RAMP,
                    help=f"comma-separated concurrency ladder "
                         f"(default {DEFAULT_RAMP})")
    ap.add_argument("--level-requests", type=int, default=128,
                    help="closed-loop requests per ramp level")
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--timeout-ms", type=float, default=60000.0,
                    help="per-request deadline handed to the server")
    ap.add_argument("--sample", default=None,
                    help="servescope sampling (rate in (0,1] or an "
                         "every-Nth stride; default: trace everything)")
    ap.add_argument("--devicescope", type=int, default=0,
                    help="capture a devicescope window over N dispatches "
                         "of the final ramp level (0 = off)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="drive an N-replica fleet behind the Router "
                         "instead of one ModelServer (0 = off)")
    ap.add_argument("--fleet-cache", default=None,
                    help="shared AOT compile-cache dir for --fleet "
                         "(default: <out>_cache)")
    ap.add_argument("--out", default="/tmp/mxtpu_serve_load.json")
    ap.add_argument("--events", default=None,
                    help="write the mxtpu.events/1 request/batch stream "
                         "here (default: alongside --out)")
    args = ap.parse_args(argv)

    ramp = sorted({int(t) for t in args.ramp.split(",") if t.strip()})
    if not ramp:
        print("serve_load: empty --ramp", file=sys.stderr)
        return 2
    fleet_n = max(0, int(args.fleet))
    bench_name = (f"{args.model}_fleet{fleet_n}" if fleet_n
                  else args.model)
    metric = f"serve_load_{bench_name}_qps_at_knee"
    events_path = args.events or (
        os.path.splitext(args.out)[0] + "_events.jsonl")

    import numpy as np

    # runnable from anywhere: the repo root is this file's parent dir
    _root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _root not in sys.path:
        sys.path.insert(0, _root)
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import (commscope, devicescope, fleetscope,
                                     perfscope, servescope, serving)
    from incubator_mxnet_tpu.healthmon import events as hm_events
    from incubator_mxnet_tpu.models import get_model

    shapes = {"lenet": (1, 28, 28), "resnet50_v1": (224, 224, 3)}
    if args.model not in shapes:
        print(f"serve_load: no serving shape for {args.model!r} "
              f"(choose from {sorted(shapes)})", file=sys.stderr)
        return 2
    shape = shapes[args.model]

    # arm the observability stack: perfscope+commscope so every bucket
    # carries its roofline + resharding verdict, servescope for the
    # attribution, and the event log for the correlation stream
    perfscope.enable()
    commscope.enable()
    servescope.enable(sample=args.sample)
    # fleetscope: every client request carries a minted traceparent, and
    # the router/server side joins it (extra.fleetscope reports the rate)
    fleetscope.enable()
    run_id = f"serveload-{os.getpid()}-{int(time.time())}"
    hm_events.open_log(events_path, run_id=run_id, rank=0)

    kwargs = {"layout": "NHWC"} if args.model.startswith("resnet") else {}

    def make_model(compile_cache=None):
        net = get_model(args.model,
                        classes=10 if args.model == "lenet" else 1000,
                        **kwargs)
        net.initialize(init=mx.init.Xavier())
        return net.freeze(input_shape=shape, compile_cache=compile_cache)

    rset = router = srv = coll = None
    replica_events_tmpl = (os.path.splitext(events_path)[0]
                           + "_replica_{pid}.jsonl")
    buckets_list = []
    if fleet_n:
        from incubator_mxnet_tpu import fleet as fleet_mod
        cache_dir = args.fleet_cache or \
            (os.path.splitext(args.out)[0] + "_cache")
        # spawned workers: each replica is its own PROCESS (own GIL —
        # in-process replicas cannot out-scale one bare server), warmed
        # through the shared on-disk AOT cache. servescope/fleetscope in
        # the spec arm replica-side spans + trace joining; export gives
        # the fleetscope collector its pull target; {pid} keeps the
        # per-replica events logs apart (worker substitutes its PID)
        spec = {"model": args.model,
                "classes": 10 if args.model == "lenet" else 1000,
                "model_kwargs": kwargs,
                "input_shape": list(shape),
                "batcher": "continuous",
                "cache_dir": cache_dir,
                "servescope": True,
                "fleetscope": True,
                "export": True,
                "events": {"path": replica_events_tmpl,
                           "run_id": run_id, "rank": 0},
                "server": {"max_delay_ms": args.max_delay_ms,
                           "queue_limit": max(256, ramp[-1] * 4),
                           "default_timeout_ms": args.timeout_ms}}
        print(f"serve_load: spawning {fleet_n} {args.model} worker "
              f"processes (shared AOT cache at {cache_dir})")
        rset = fleet_mod.ReplicaSet(spec, n=fleet_n, spawn=True)
        rset.start()
        router = fleet_mod.Router(rset)
        host, port = router.start()
        targets = [{"name": rep.name, "host": rep.host,
                    "port": rep.diag_port}
                   for rep in rset.replicas if rep.diag_port]
        if targets:
            # clock-offset estimation + live counters over each worker's
            # diagnostics.export endpoint, for the whole sweep
            coll = fleetscope.Collector(targets, interval_s=1.0).start()
        try:
            _, r0 = rset.replicas[0].http_get("/stats")
            buckets_list = list(r0.get("buckets") or [])
        except Exception:  # noqa: BLE001 — cosmetic only
            pass
        print(f"serve_load: {args.model} fleet({fleet_n}) router at "
              f"{router.address} buckets={buckets_list} ramp={ramp} "
              f"x{args.level_requests} req/level")
    else:
        print(f"serve_load: freezing {args.model} (AOT compile + warmup)")
        frozen = make_model()
        srv = serving.ModelServer(
            frozen, max_delay_ms=args.max_delay_ms,
            queue_limit=max(256, ramp[-1] * 4),
            default_timeout_ms=args.timeout_ms)
        host, port = srv.start()
        buckets_list = list(frozen.buckets)
        print(f"serve_load: {args.model} at {srv.address} "
              f"buckets={frozen.buckets} ramp={ramp} "
              f"x{args.level_requests} req/level")

    import http.client
    rng = np.random.RandomState(0)
    samples = rng.rand(64, *shape).astype(np.float32)
    bodies = [json.dumps({"data": s.tolist(),
                          "timeout_ms": args.timeout_ms}).encode()
              for s in samples]

    # keep-alive connection per client thread (the wrk/hey load-gen
    # convention): a closed-loop client measures the SERVING path, not
    # per-request TCP connect — without reuse, a concurrent burst
    # overflows accept backlogs and the "p99" becomes kernel SYN
    # retransmit timeouts (measured: exact 1s/3s modes)
    tls = threading.local()

    # --fleet: client-observed per-replica latencies, keyed off the
    # `replica` tag the router stamps into every reply (the ONLY place
    # per-replica p99 exists: the in-process replicas share one metrics
    # registry, so server-side counters are already fleet-aggregated)
    fleet_lock = threading.Lock()
    fleet_lats = {}
    # client-side trace accounting: every request mints a fresh
    # traceparent; "echo" counts replies whose trace_id matches (the
    # single-server join — fleet mode joins the events files instead)
    fs_counts = {"minted": 0, "ok": 0, "echo": 0}

    def send(i):
        conn = getattr(tls, "conn", None)
        if conn is None:
            conn = tls.conn = http.client.HTTPConnection(
                host, port, timeout=120)
            conn.connect()
            import socket as _socket
            conn.sock.setsockopt(_socket.IPPROTO_TCP,
                                 _socket.TCP_NODELAY, 1)
        headers = {"Content-Type": "application/json"}
        tp = None
        if fleetscope.enabled():
            tp = fleetscope.mint()
            headers["traceparent"] = tp.header()
            with fleet_lock:
                fs_counts["minted"] += 1
        t0 = time.perf_counter()
        try:
            conn.request("POST", "/predict", body=bodies[i % len(bodies)],
                         headers=headers)
            r = conn.getresponse()
            data = r.read()
            if r.status != 200:
                raise RuntimeError(f"HTTP {r.status}: {data[:120]!r}")
        except Exception:
            try:
                conn.close()
            finally:
                tls.conn = None
            raise
        doc = None
        if fleet_n or tp is not None:
            try:
                doc = json.loads(data)
            except ValueError:
                doc = None
        if tp is not None:
            with fleet_lock:
                fs_counts["ok"] += 1
                if isinstance(doc, dict) \
                        and doc.get("trace_id") == tp.trace_id:
                    fs_counts["echo"] += 1
        if fleet_n:
            dt_ms = (time.perf_counter() - t0) * 1e3
            rep = doc.get("replica") if isinstance(doc, dict) else None
            if rep:
                with fleet_lock:
                    fleet_lats.setdefault(rep, []).append(dt_ms)

    win = None

    def _arm_window(li, c):
        # measured device window over the most loaded level: the
        # attribution's device_exec upgrades to measured(profile)
        # when it completes
        nonlocal win
        if args.devicescope > 0 and li == len(ramp) - 1:
            win = devicescope.capture(steps=args.devicescope).start()

    try:
        levels = sweep(send, ramp, args.level_requests,
                       before_level=_arm_window)
    except ServerDied as e:
        print(f"serve_load: SERVER DIED — writing env_failure artifact: "
              f"{e}", file=sys.stderr)
        write_env_failure(args.out, metric, str(e))
        hm_events.close_log()
        if coll is not None:
            coll.stop()
        if router is not None:
            router.stop()
        if rset is not None:
            rset.stop(drain=False)
        return 0
    finally:
        if win is not None:
            win.stop()

    knee_idx, reason = find_knee(levels)
    # ONE cumulative snapshot per replica. Spawned replicas each own a
    # metrics registry, so the fleet-wide serving section is the MERGE
    # of their /stats exports (counters sum, histograms merge by
    # bucket).
    if fleet_n:
        snaps = []
        for rep in rset.replicas:
            try:
                code, s = rep.http_get("/stats")
                if code == 200:
                    snaps.append(s)
            except Exception as e:  # noqa: BLE001 — partial fleet stats
                print(f"serve_load: /stats from {rep.name} failed: {e}",
                      file=sys.stderr)
        stats = merge_serving_stats(snaps)
    else:
        stats = srv.stats()
    fleet_meta = None
    if fleet_n:
        router_stats = router.stats()
        sweep_wall = sum(lv["wall_s"] for lv in levels) or 1.0
        rows = []
        for rep in rset.replicas:
            lats = sorted(fleet_lats.get(rep.name, []))
            row = {"name": rep.name, "requests": len(lats),
                   "qps": round(len(lats) / sweep_wall, 2),
                   "dispatched": router_stats.get(
                       "dispatch_counts", {}).get(rep.name, 0)}
            if lats:
                row.update(p50_ms=round(_percentile(lats, 0.50), 3),
                           p95_ms=round(_percentile(lats, 0.95), 3),
                           p99_ms=round(_percentile(lats, 0.99), 3))
            rows.append(row)
        fleet_meta = {
            "replicas": fleet_n,
            "batcher": "continuous",
            "cache_dir": cache_dir,
            "per_replica": rows,
            "dispatch_counts": router_stats.get("dispatch_counts"),
            "dispatch_imbalance": round(
                router_stats.get("dispatch_imbalance", 0.0), 4),
            "routed": int(router_stats.get("fleet.routed", 0)),
            "routed_errors": int(
                router_stats.get("fleet.routed_errors", 0)),
            "no_replica_available": int(
                router_stats.get("fleet.no_replica_available", 0)),
            # worker-reported warmup cache traffic (each worker owns
            # its registry; the readiness handshake carries these)
            "compile_cache": {
                key: sum(int((rep.cache_stats or {}).get(key, 0))
                         for rep in rset.replicas)
                for key in ("hits", "misses", "stores")
            },
        }
    # spawned replicas trace their own spans in their own processes —
    # the parent has no servescope data to attribute in fleet mode
    servescope_extra = None if fleet_n else servescope.bench_extra()
    ds_extra = devicescope.bench_extra() if win is not None else None
    # child PIDs locate the per-replica events files; grab them before
    # the processes are reaped
    replica_pids = []
    if fleet_n:
        replica_pids = [(rep.name, rep.proc.pid)
                        for rep in rset.replicas if rep.proc is not None]
        if coll is not None:
            coll.stop()
        router.stop()
        rset.stop(drain=True)
    else:
        srv.stop()
    hm_events.close_log()

    # join the traces: fleet mode joins the router's fleetscope.request
    # records (harness events file) against each worker's
    # serving.request records; single-server mode uses the reply echo
    # (the server runs in-process — there is no wire gap to measure)
    fs_extra = None
    if fleetscope.enabled():
        if fleet_n:
            replica_recs = []
            for _name, pid in replica_pids:
                replica_recs += read_event_records(
                    replica_events_tmpl.replace("{pid}", str(pid)),
                    "serving.request")
            fs_extra = build_fleetscope_extra(
                fs_counts["minted"],
                read_event_records(events_path, "fleetscope.request"),
                replica_recs)
            if coll is not None:
                fs_extra["collector"] = coll.snapshot()
        else:
            ok, echo = fs_counts["ok"], fs_counts["echo"]
            fs_extra = {
                "client_minted": fs_counts["minted"],
                "sampled": ok,
                "joined": echo,
                "unjoined_forwards": ok - echo,
                "join_rate": round(echo / ok, 6) if ok else 0.0,
            }

    meta = {"run_id": run_id, "events_file": events_path,
            "buckets": buckets_list,
            "max_delay_ms": args.max_delay_ms,
            "level_requests": args.level_requests}
    if fleet_meta is not None:
        meta["fleet"] = fleet_meta
    if fs_extra is not None:
        meta["fleetscope"] = fs_extra
    doc = build_result(bench_name, levels, knee_idx, reason, stats,
                       servescope_extra=servescope_extra,
                       devicescope_extra=ds_extra,
                       meta=meta)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    knee = levels[knee_idx]
    print(f"serve_load: knee at concurrency {knee['concurrency']} "
          f"({reason})")
    print(f"serve_load: {doc['metric']} = {doc['value']} requests/sec, "
          f"p99 {knee['p99_ms']:.1f} ms")
    att = (servescope_extra or {}).get("advice")
    if att:
        print(f"serve_load: attribution: {att}")
    if fs_extra is not None:
        gap = (fs_extra.get("wire_gap_ms") or {}).get("p50")
        print(f"serve_load: fleetscope: {fs_extra['joined']}/"
              f"{fs_extra['sampled']} traces joined (join_rate "
              f"{fs_extra['join_rate']:.3f}, "
              f"{fs_extra['client_minted']} client-minted"
              + (f", wire gap p50 {gap:.2f} ms" if gap is not None
                 else "") + ")")
    print(f"serve_load: wrote {args.out} (events: {events_path})")

    # self-check: the artifact must validate before anything gates on it
    # (fleet mode: every replica's events file too)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_check
    errors = trace_check.check_file(args.out) \
        + trace_check.check_file(events_path)
    for _name, pid in replica_pids:
        p = replica_events_tmpl.replace("{pid}", str(pid))
        if os.path.exists(p):
            errors += trace_check.check_file(p)
    if errors:
        for e in errors:
            print(f"serve_load: ARTIFACT INVALID: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
