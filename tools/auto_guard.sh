#!/bin/bash
# Round-5 guard reactor. The relay wedged at ~11:50 UTC during a fresh
# k=5 scan compile (docs/AUTOSWEEP_r05.log); the cache already holds the
# driver-default programs (22.6 MB step + k8 scan). If the tunnel heals,
# the highest-value move is to CONFIRM the driver-default bench runs
# from cache — one cheap run — and then leave the tunnel alone for the
# driver's protected end-of-round bench. Unlike auto_sweep it launches
# NO fresh large compiles (the k5 compile is what wedged the relay).
LOG=${1:-/root/repo/docs/AUTOSWEEP_r05.log}
cd /root/repo || exit 1
echo "$(date -u +%F' '%T) auto_guard armed (pid $$)" >> "$LOG"
# mxlint static gate FIRST (seconds, no backend): zero findings on the
# tree gates the run — a knob read that bypasses the resolution order or
# a drifted counter family invalidates every measurement below
if timeout 300 python tools/mxlint.py --check >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) mxlint gate OK (0 findings)" >> "$LOG"
else
  echo "$(date -u +%F' '%T) mxlint gate FAILED — tree has findings; aborting (fix or suppress with a reason)" >> "$LOG"
  exit 1
fi
# mxlint strict-mode smoke (CPU lenet under MXTPU_STRICT=1): zero
# transfer-guard trips + zero steady-state recompiles, trace_check-valid
if timeout 900 bash tools/mxlint_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) mxlint smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) mxlint smoke FAILED (continuing; steady-loop hygiene suspect)" >> "$LOG"
fi
# CPU-side observability smoke BEFORE touching the tunnel: if the
# diagnostics/telemetry pipeline is broken, find out here (cheap) rather
# than after burning tunnel time on an unmeasurable bench run.
if timeout 900 bash tools/diag_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) diag smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) diag smoke FAILED (continuing; bench telemetry suspect)" >> "$LOG"
fi
# serving-path smoke (CPU-only, same discipline): batching + latency
# telemetry must hold before any tunnel time is spent
if timeout 900 bash tools/serve_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) serve smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) serve smoke FAILED (continuing; serving path suspect)" >> "$LOG"
fi
# fleet smoke (CPU-only): continuous batching live under load,
# zero-drop draining deploys, and the 2-replica spawned fleet's
# artifacts must validate before any fleet claim is trusted
if timeout 1200 bash tools/fleet_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) fleet smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) fleet smoke FAILED (continuing; fleet path suspect)" >> "$LOG"
fi
# healthmon smoke (CPU-only 2-proc cluster + overhead budget): cross-rank
# health must hold before trusting any distributed run's telemetry
if timeout 1200 bash tools/health_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) health smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) health smoke FAILED (continuing; healthmon suspect)" >> "$LOG"
fi
# whole-loop executor smoke (CPU-only): 50 lenet steps through
# mxtpu.trainloop — loss decreases, io.*/trainloop.* telemetry present,
# dispatches_per_step < 1
if timeout 900 bash tools/trainloop_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) trainloop smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) trainloop smoke FAILED (continuing; whole-loop executor suspect)" >> "$LOG"
fi
# ingest-pipeline smoke (CPU-only): serial vs pipelined lenet with an
# injected slow decode — the pool must cut io.wait_ms, the overlap
# inequality must hold, and the decode-starvation triage must render
if timeout 1200 bash tools/io_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) io smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) io smoke FAILED (continuing; ingest pipeline suspect)" >> "$LOG"
fi
# perfscope smoke (CPU-only): step-time decomposition sums, roofline
# verdicts present, and the perf_regress gate passes self-vs-self /
# fails on an injected regression / skips env_failure artifacts
if timeout 900 bash tools/perfscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) perfscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) perfscope smoke FAILED (continuing; perf attribution suspect)" >> "$LOG"
fi
# sharding smoke (CPU-only 4-fake-device mesh matrix): dp/mp/fsdp loss
# parity + sharding.* counters + FSDP memory reduction
if timeout 1800 bash tools/shard_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) shard smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) shard smoke FAILED (continuing; sharded executor suspect)" >> "$LOG"
fi
# commscope smoke (CPU-only fsdp4 mesh): collective inventory nonzero,
# resharding detector quiet, step-budget collective provenance=estimated
if timeout 900 bash tools/comms_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) comms smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) comms smoke FAILED (continuing; collective observability suspect)" >> "$LOG"
fi
# devicescope smoke (CPU-only): measured capture window, busy fraction,
# top-K program join, reconciliation + provenance upgrade, rotation
if timeout 1200 bash tools/devicescope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) devicescope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) devicescope smoke FAILED (continuing; measured device timeline suspect)" >> "$LOG"
fi
# servescope smoke (CPU-only 64-client load sweep): tail-latency
# attribution sums within 15%, bucket verdicts present, knee found,
# perf_regress flags an injected p99 degradation
if timeout 1200 bash tools/servescope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) servescope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) servescope smoke FAILED (continuing; serving attribution suspect)" >> "$LOG"
fi
# resilience smoke (CPU-only chaos harness + resilient bench): NaN
# rollback, torn-checkpoint fallback, stall restart, and elastic
# rank kill/re-join must all SELF-HEAL with the recovery on every
# telemetry surface before any long run is trusted to survive one
if timeout 1800 bash tools/resilience_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) resilience smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) resilience smoke FAILED (continuing; self-healing suspect)" >> "$LOG"
fi
# autotune smoke (CPU-only): bounded knob search with measured(profile)
# provenance, winner busy >= stepwise default, cache hit = 0 trials
if timeout 1800 bash tools/autotune_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) autotune smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) autotune smoke FAILED (continuing; knob tuner suspect)" >> "$LOG"
fi
# memscope smoke (CPU-only): static footprints joined to rooflines,
# bounded watermark ring, headroom verdict, and the autotuner's
# memory-feasibility pruner rejecting an over-capacity batch candidate
# pre-trial (reason=memory, zero subprocess spent)
if timeout 1800 bash tools/memscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) memscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) memscope smoke FAILED (continuing; memory observability suspect)" >> "$LOG"
fi
# embedding smoke (CPU-only mp4 mesh): 50 recsys/DLRM steps with the
# vocab-sharded tables, dedup lookup, and row-sparse AdaGrad — loss
# must fall, per-device table bytes must beat replicated, the lookup
# collective must attribute to the mp axis, and the resharding
# detector must stay quiet on the annotated layout
if timeout 1200 bash tools/embedding_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) embedding smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) embedding smoke FAILED (continuing; embedding subsystem suspect)" >> "$LOG"
fi
# fleetscope smoke (CPU-only 2-replica spawned fleet): every request
# carries a client-minted traceparent end to end — >= 95% of traces
# must join router-to-replica, the wire-gap + replica-span accounting
# must reconstruct the router e2e, the collector must pull every
# replica with a bounded clock offset, and mxdiag trace/pod must
# render the merged story from the artifacts alone
if timeout 1200 bash tools/fleetscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) fleetscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) fleetscope smoke FAILED (continuing; cross-process tracing suspect)" >> "$LOG"
fi
while true; do
  ts=$(date -u +%H:%M)
  timeout 300 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
print(float((x @ x).sum()))
" >/dev/null 2>&1
  rc=$?
  echo "$ts guard probe rc=$rc" >> "$LOG"
  if [ "$rc" = "0" ]; then
    echo "$ts TUNNEL HEALED -> one cached driver-default bench, then quiet" >> "$LOG"
    timeout 1800 python bench.py > /tmp/mxtpu_guard_bench.json 2>> "$LOG"
    brc=$?
    cat /tmp/mxtpu_guard_bench.json >> "$LOG"
    echo "$(date -u +%F' '%T) guard bench rc=$brc" >> "$LOG"
    # regression gate: the fresh number vs the repo's BENCH trajectory
    # (env_failure artifacts — the r02-r05 hangs — are skipped, so an
    # empty baseline pool just reports OK)
    timeout 120 python tools/perf_regress.py --dir . \
      --candidate /tmp/mxtpu_guard_bench.json >> "$LOG" 2>&1
    echo "$(date -u +%F' '%T) perf_regress rc=$?; auto_guard exiting (tunnel left alone)" >> "$LOG"
    exit 0
  fi
  sleep 600
done
