#!/usr/bin/env python
"""Healthmon 2-process cluster exercise: the acceptance harness for
cross-rank training health (tools/health_smoke.sh runs it; the tier-1
test tests/test_healthmon_cluster.py asserts on its output).

Parent mode (default): spawns a REAL 2-process jax cluster over loopback
gloo (the same bootstrap tests/test_multihost_real.py exercises), with

* an injected straggler — rank 1 sleeps ``MXTPU_HM_TEST_SLEEP_MS``
  (default 80) before every forward, and
* an injected NaN — rank 0's observed loss is NaN at step
  ``MXTPU_HM_NAN_STEP`` (default 7),

then asserts the healthmon contract end to end:

* ``healthmon.collective_skew_ms`` reports the injected skew and
  ``healthmon.slowest_rank`` attributes it to rank 1 on EVERY rank
  (the verdict is computed from the exchanged table, so fast ranks
  know who is slow);
* the NaN raised a watchdog alert (counter + flight event + structured
  log record) on rank 0;
* each rank's ``mxtpu.events/1`` log and flight dump validate, and
  ``mxdiag merge`` interleaves them into one cross-rank timeline that
  shows both ranks, the skew report, and the NaN alert.

Worker mode (``--worker PID NPROC PORT STEPS``): one rank of the
cluster — tiny dense model, gluon.Trainer over a ``dist_sync`` kvstore
(so every step runs a real cross-process collective), healthmon armed
with a 5-step exchange cadence and the every-3-steps grad-norm sentinel.

Exit 0 iff every assertion holds; prints ``HEALTH_SMOKE_OK {json}``.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

STEPS = int(os.environ.get("MXTPU_HM_TEST_STEPS", "20"))
SLEEP_MS = float(os.environ.get("MXTPU_HM_TEST_SLEEP_MS", "80"))
NAN_STEP = int(os.environ.get("MXTPU_HM_NAN_STEP", "7"))
WORKER_TIMEOUT_S = int(os.environ.get("MXTPU_TEST_WORKER_TIMEOUT", "420"))


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def worker(pid: int, nproc: int, port: str, steps: int) -> None:
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu import diagnostics as diag
    from incubator_mxnet_tpu.profiler.counters import counters

    out_dir = os.environ["MXTPU_HM_OUT"]
    mx.distributed.init(coordinator_address=f"127.0.0.1:{port}",
                        num_processes=nproc, process_id=pid)
    rank = mx.distributed.rank()
    diag.enable_flight_recorder(dump_on_crash=False, dump_dir=out_dir)
    mon = mx.healthmon.enable(hm_dir=out_dir, exchange_every=5,
                              stall_timeout_s=0, grad_norm_every=3)

    np.random.seed(0)
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="dist_sync")
    L = gluon.loss.L2Loss()
    x = nd.array(np.random.rand(8, 6).astype(np.float32))
    y = nd.array(np.random.rand(8, 4).astype(np.float32))

    for i in range(1, steps + 1):
        if rank == 1 and SLEEP_MS > 0:
            time.sleep(SLEEP_MS / 1e3)   # the injected straggler
        with mx.autograd.record():
            loss = L(net(x), y).mean()
        loss.backward()
        trainer.step(8)
        val = float(loss.asscalar())
        if rank == 0 and i == NAN_STEP:
            val = float("nan")           # the injected divergence
        mx.healthmon.observe_loss(val, step=i)

    flight_path = diag.dump_flight(
        reason="health_worker",
        path=os.path.join(out_dir, f"flight_rank{rank}.json"))
    snap = {k: v for k, v in counters().items()
            if k.startswith("healthmon/")}
    events_path = mon.events.path
    mx.healthmon.disable()
    print("HEALTH " + json.dumps({
        "rank": rank, "counters": snap,
        "events_file": events_path, "flight_file": flight_path}),
        flush=True)
    mx.distributed.barrier()
    mx.distributed.shutdown()
    print("WORKER_DONE", flush=True)


# ---------------------------------------------------------------------------
# parent
# ---------------------------------------------------------------------------

def _free_port() -> int:
    """Coordinator port outside the ephemeral range (see
    tests/test_multihost_real.py for the rationale)."""
    base = 23000 + (os.getpid() * 131) % 500
    for off in range(1000):
        port = 23000 + (base - 23000 + off) % 1000
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", port))
        except OSError:
            continue
        finally:
            s.close()
        return port
    raise RuntimeError("no free coordination port in 23000-23999")


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    out_dir = os.environ.get("MXTPU_HM_OUT",
                             "/tmp/mxtpu_health_cluster")
    import shutil
    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    port = str(_free_port())
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # workers pin their own device count
    env["MXTPU_HM_OUT"] = out_dir
    env.setdefault("MXTPU_RUN_ID", f"health-smoke-{int(time.time())}")
    env.setdefault("MXTPU_INIT_TIMEOUT", "180")

    print(f"health_cluster: 2-proc cluster, {STEPS} steps, "
          f"rank-1 sleep {SLEEP_MS}ms, NaN at step {NAN_STEP} "
          f"-> {out_dir}", flush=True)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         str(pid), "2", port, str(STEPS)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=_REPO) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=WORKER_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        if rc != 0:
            print(f"health_cluster: worker failed rc={rc}\n"
                  f"stdout:{out}\nstderr:{err[-3000:]}", file=sys.stderr)
            return 1

    reports = {}
    for _, out, _ in outs:
        for ln in out.splitlines():
            if ln.startswith("HEALTH "):
                doc = json.loads(ln[len("HEALTH "):])
                reports[doc["rank"]] = doc
    assert sorted(reports) == [0, 1], f"missing rank reports: {reports}"

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    sleep_floor = 0.4 * SLEEP_MS
    for rank, doc in sorted(reports.items()):
        c = doc["counters"]
        check(c.get("healthmon/healthmon.steps") == STEPS,
              f"rank {rank}: steps counter {c.get('healthmon/healthmon.steps')} != {STEPS}")
        check(c.get("healthmon/healthmon.exchanges", 0) >= STEPS // 5,
              f"rank {rank}: too few exchanges: {c}")
        skew = c.get("healthmon/healthmon.collective_skew_ms", 0)
        check(skew >= sleep_floor,
              f"rank {rank}: skew {skew}ms < floor {sleep_floor}ms "
              f"(injected {SLEEP_MS}ms)")
        check(c.get("healthmon/healthmon.slowest_rank") == 1,
              f"rank {rank}: slowest_rank "
              f"{c.get('healthmon/healthmon.slowest_rank')} != 1")
        check("healthmon/healthmon.grad_global_norm" in c,
              f"rank {rank}: grad-norm gauge missing")
    check(reports[0]["counters"].get(
        "healthmon/healthmon.nan_alerts", 0) >= 1,
        "rank 0: injected NaN raised no alert")

    # artifacts: per-rank validation + the merged cross-rank timeline
    tc = _load_tool("trace_check")
    md = _load_tool("mxdiag")
    artifact_errors = []
    paths = []
    for rank, doc in sorted(reports.items()):
        artifact_errors += tc.check_events_jsonl(doc["events_file"])
        artifact_errors += tc.check_flight(doc["flight_file"])
        paths += [doc["events_file"], doc["flight_file"]]
    merged_path = os.path.join(out_dir, "merged.jsonl")
    merged = md.merge_timelines(paths, out_path=merged_path)
    artifact_errors += tc.check_events_jsonl(merged_path)
    check(not artifact_errors, f"artifact validation: {artifact_errors[:5]}")

    merged_ranks = {r["rank"] for r in merged}
    check(merged_ranks >= {0, 1},
          f"merged timeline missing ranks: {sorted(merged_ranks)}")
    check(any(r["name"] == "skew_report" for r in merged),
          "merged timeline has no skew_report")
    check(any(r["name"] == "healthmon.nan_loss" for r in merged),
          "merged timeline has no NaN alert")
    nan_steps = [r["step"] for r in merged
                 if r["name"] == "healthmon.nan_loss"]
    check(NAN_STEP in nan_steps,
          f"NaN alert not attributed to step {NAN_STEP}: {nan_steps}")

    if failures:
        for f in failures:
            print(f"health_cluster: FAIL: {f}", file=sys.stderr)
        return 1
    summary = {
        "skew_ms": reports[0]["counters"].get(
            "healthmon/healthmon.collective_skew_ms"),
        "slowest_rank": reports[0]["counters"].get(
            "healthmon/healthmon.slowest_rank"),
        "nan_alerts_rank0": reports[0]["counters"].get(
            "healthmon/healthmon.nan_alerts"),
        "merged_records": len(merged), "merged_file": merged_path}
    print("HEALTH_SMOKE_OK " + json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=2"
        sys.path.insert(0, _REPO)
        import jax
        jax.config.update("jax_platforms", "cpu")
        worker(int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
               int(sys.argv[5]))
        sys.exit(0)
    sys.exit(main())
