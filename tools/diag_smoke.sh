#!/bin/bash
# Tier-1 diagnostics smoke: run a few bench steps ON CPU with the full
# observability stack armed (memory ledger + 100ms metrics sampler +
# flight recorder), then validate every artifact with tools/trace_check
# and assert the BENCH json carries the memory/counters sections.
# No TPU, no tunnel — safe to run anywhere, cheap enough for CI.
# Exit 0 iff the whole pipeline (record -> export -> validate) is healthy.
set -u
cd "$(dirname "$0")/.." || exit 1

DIAG_DIR=${MXTPU_DIAG_DIR:-/tmp/mxtpu_diag_smoke}
OUT=${1:-/tmp/mxtpu_diag_smoke_bench.json}
rm -rf "$DIAG_DIR"; mkdir -p "$DIAG_DIR"

echo "diag_smoke: 3 lenet bench steps on CPU, sampler 100ms + flight on"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=3 \
  BENCH_DTYPE=float32 BENCH_DIAG=1 BENCH_DIAG_INTERVAL_MS=100 \
  MXTPU_DIAG_DIR="$DIAG_DIR" \
  BENCH_TRACE_FILE="$DIAG_DIR/trace.json" \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$DIAG_DIR/bench.log"
rc=$?
if [ "$rc" != "0" ]; then
  echo "diag_smoke: bench.py failed rc=$rc"; tail -30 "$DIAG_DIR/bench.log"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
extra = doc.get("extra") or {}
mem = extra.get("memory") or {}
assert mem.get("peak_bytes", 0) > 0, "no memory peak in BENCH json"
assert isinstance(extra.get("counters"), dict) and extra["counters"], \
    "no counters snapshot in BENCH json"
assert extra.get("flight_file"), "no flight dump recorded"
print(f"diag_smoke: bench OK ({doc['value']} {doc['unit']}, "
      f"peak {mem['peak_bytes']} bytes, "
      f"{len(extra['counters'])} counters)")
EOF

# validate every telemetry artifact; trace_check exits non-zero on any
# schema violation or non-monotonic counter
FLIGHT=$(python -c "import json,sys;print(json.load(open('$OUT'))['extra']['flight_file'])")
python tools/trace_check.py \
  "$DIAG_DIR/trace.json" "$FLIGHT" \
  "$DIAG_DIR/metrics.jsonl" "$DIAG_DIR/metrics.prom" || exit 1

# the dump must also be pretty-printable
python tools/mxdiag.py "$FLIGHT" --events 5 > /dev/null || exit 1
echo "diag_smoke: all telemetry artifacts validate"
