#!/usr/bin/env python
"""Chaos harness for mxtpu.resilience: inject real faults, assert real
recovery (tools/resilience_smoke.sh runs it; the tier-1 test
tests/test_resilience.py::test_chaos_* asserts on its output). The
health_cluster.py pattern, escalated from detection to self-healing:
healthmon's harness proves the verdicts fire; THIS one proves training
outlives them.

Scenarios (``--scenario nan|torn|freeze|kill|all``; all = default):

* **nan** — a poison batch (NaN feature) lands mid-run in a supervised
  TrainLoop: the loss goes non-finite, the Supervisor rolls back to the
  last good async checkpoint, skips the batch, and the run converges.
* **torn** — phase 1 trains and checkpoints, the parent CORRUPTS the
  newest checkpoint on disk (bit-flip in the largest payload file),
  phase 2 restarts: restore detects the torn checkpoint via its
  manifest digests, falls back to the previous good one (counted +
  evented), resumes past the consumed batches, and converges.
* **freeze** — the data source wedges forever mid-run: the stall
  watchdog fires, the Supervisor (``on_stall=exit``) dies with
  RESTART_EXIT_CODE, the parent restarts it, and the resumed run
  converges from last-good.
* **kill** — a 2-rank elastic group (rank-0 TCP coordinator) trains
  data-parallel by model averaging; rank 1 SIGKILLs itself MID-STEP:
  rank 0's round deadline evicts it, the survivor rolls back to
  last-good and keeps training at world size 1; the parent then
  relaunches rank 1, which re-joins at the checkpoint boundary and
  both finish. Merged cross-rank timeline validates.

Every scenario asserts the three-surface contract: >= 1 recovery in
the ``resilience.*`` counters, in the flight ring, AND in the
``mxtpu.events/1`` log — plus loss decreasing through the fault and a
clean ``mxdiag.py recover`` rendering.

Exit 0 iff every assertion holds; prints ``CHAOS_OK {json}``.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

STEPS = int(os.environ.get("MXTPU_CHAOS_STEPS", "24"))
NAN_BATCH = int(os.environ.get("MXTPU_CHAOS_NAN_BATCH", "9"))
KILL_STEP = int(os.environ.get("MXTPU_CHAOS_KILL_STEP", "8"))
FREEZE_BATCH = int(os.environ.get("MXTPU_CHAOS_FREEZE_BATCH", "8"))
WORKER_TIMEOUT_S = int(os.environ.get("MXTPU_TEST_WORKER_TIMEOUT", "300"))
CKPT_EVERY = int(os.environ.get("MXTPU_CHAOS_CKPT_EVERY", "4"))


# ---------------------------------------------------------------------------
# shared worker plumbing
# ---------------------------------------------------------------------------

def _toy(seed=0):
    """Deterministic toy regression: y = x @ W. Loss must DECREASE
    through every injected fault — that is the acceptance bar."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize(init=mx.init.Xavier())
    return net, gluon.loss.L2Loss()


_W = None


def _batch(i, poison=False):
    import numpy as np
    global _W
    if _W is None:
        _W = np.random.RandomState(7).randn(8, 1).astype(np.float32)
    r = np.random.RandomState(1000 + i)
    x = r.randn(16, 8).astype(np.float32)
    if poison:
        x[0, 0] = np.nan
    return (x, (x @ _W).astype(np.float32))


def _arm_telemetry(out_dir, tag, stall_s=0):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import diagnostics as diag
    diag.enable_flight_recorder(dump_on_crash=False, dump_dir=out_dir)
    mon = mx.healthmon.enable(
        hm_dir=out_dir, stall_timeout_s=stall_s, exchange_every=0,
        events_path=os.path.join(out_dir, f"events_{tag}.jsonl"),
        stall_check_interval_s=0.25 if stall_s else None)
    return mon


def _finish(tag, mon, extra):
    """Worker epilogue: flight dump + counters snapshot on stdout."""
    from incubator_mxnet_tpu import diagnostics as diag
    from incubator_mxnet_tpu.profiler.counters import counters
    import incubator_mxnet_tpu as mx
    out_dir = os.environ["MXTPU_CHAOS_OUT"]
    flight_path = diag.dump_flight(
        reason=f"chaos_{tag}",
        path=os.path.join(out_dir, f"flight_{tag}.json"))
    snap = {k: v for k, v in counters().items()
            if (k.startswith("resilience/") or k.startswith("healthmon/"))
            and not isinstance(v, dict)}
    events_path = mon.events.path
    mx.healthmon.disable()
    print("CHAOS " + json.dumps(dict(
        extra, tag=tag, counters=snap, events_file=events_path,
        flight_file=flight_path)), flush=True)


def _loss_trend(losses):
    import numpy as np
    arr = np.asarray(losses, np.float64)
    head = float(arr[:2].mean())
    tail = float(arr[-2:].mean())
    return {"n": int(arr.size), "first": head, "last": tail,
            "decreased": bool(tail < head) and bool(np.isfinite(tail))}


# ---------------------------------------------------------------------------
# workers
# ---------------------------------------------------------------------------

def worker_nan():
    """Supervised TrainLoop with a poison batch: rollback + skip."""
    from incubator_mxnet_tpu import gluon, resilience
    from incubator_mxnet_tpu.trainloop import TrainLoop
    out_dir = os.environ["MXTPU_CHAOS_OUT"]
    mon = _arm_telemetry(out_dir, "nan")
    net, L = _toy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    loop = TrainLoop(net, L, tr, chunk=2)
    data = [_batch(i, poison=(i == NAN_BATCH)) for i in range(200)]
    sup = resilience.Supervisor(
        os.path.join(out_dir, "ckpt_nan"), every=CKPT_EVERY, keep=3)
    losses = loop.fit(data, steps=STEPS, resilience=sup)
    _finish("nan", mon, {"losses": _loss_trend(losses)})


def worker_torn(phase):
    """Phase 1 trains + checkpoints and exits; phase 2 resumes after
    the parent tore the newest checkpoint."""
    from incubator_mxnet_tpu import gluon, resilience
    from incubator_mxnet_tpu.trainloop import TrainLoop
    out_dir = os.environ["MXTPU_CHAOS_OUT"]
    mon = _arm_telemetry(out_dir, f"torn{phase}")
    net, L = _toy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    loop = TrainLoop(net, L, tr, chunk=2)
    data = [_batch(i) for i in range(400)]
    ckpt_dir = os.path.join(out_dir, "ckpt_torn")
    sup = resilience.Supervisor(ckpt_dir, every=CKPT_EVERY, keep=4)
    target = STEPS // 2 if phase == 1 else STEPS
    losses = loop.fit(data, steps=target, resilience=sup)
    from incubator_mxnet_tpu.parallel import list_steps
    _finish(f"torn{phase}", mon,
            {"losses": _loss_trend(losses), "ckpt_dir": ckpt_dir,
             "ckpt_steps": list_steps(ckpt_dir)})


def worker_freeze(phase):
    """Phase 1 wedges mid-run (frozen data source) -> stall watchdog ->
    RESTART_EXIT_CODE; phase 2 is the supervised restart."""
    from incubator_mxnet_tpu import gluon, resilience
    from incubator_mxnet_tpu.trainloop import TrainLoop
    out_dir = os.environ["MXTPU_CHAOS_OUT"]
    # phase 1 proves the stall fires: the deadline must cover the
    # tiny-net compile but not much more. Phase 2 proves the RESUME
    # converges — its cold-start restore + chunk recompile must not
    # read as the stall phase 1 already proved, so it gets slack.
    mon = _arm_telemetry(out_dir, f"freeze{phase}",
                         stall_s=6.0 if phase == 1 else 20.0)
    net, L = _toy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    loop = TrainLoop(net, L, tr, chunk=2)

    def batches():
        i = 0
        while True:
            if phase == 1 and i == FREEZE_BATCH:
                time.sleep(10_000)     # the wedge: a dead input queue
            yield _batch(i)
            i += 1

    sup = resilience.Supervisor(
        os.path.join(out_dir, "ckpt_freeze"), every=CKPT_EVERY,
        keep=3, on_stall="exit")
    losses = loop.fit(batches(), steps=STEPS, resilience=sup)
    # phase 1 never reaches here (os._exit on the watchdog thread)
    _finish(f"freeze{phase}", mon, {"losses": _loss_trend(losses)})


def worker_kill(rank, rejoin=False):
    """One rank of the elastic group: local FusedTrainStep + per-step
    model averaging through ElasticGroup.sync. Rank 1 SIGKILLs itself
    MID-STEP (after local compute, before the sync) at KILL_STEP; the
    relaunched rank 1 (--rejoin) re-enters via the checkpoint boundary,
    restores last-good, and runs a few joint rounds before draining.
    A small per-step sleep keeps the round cadence slower than process
    startup so the re-join lands while rank 0 is still training."""
    import numpy as np
    from incubator_mxnet_tpu import gluon, nd, resilience
    from incubator_mxnet_tpu.parallel import (latest_step,
                                              FusedTrainStep,
                                              restore_train_step,
                                              save_train_step)
    out_dir = os.environ["MXTPU_CHAOS_OUT"]
    sleep_s = float(os.environ.get("MXTPU_CHAOS_STEP_SLEEP", "0.25"))
    tag = f"kill_r{rank}" + ("_rejoin" if rejoin else "")
    mon = _arm_telemetry(out_dir, tag)
    net, L = _toy(seed=0)            # identical init on every rank
    step = FusedTrainStep(net, L,
                          gluon.Trainer(net.collect_params(), "sgd",
                                        {"learning_rate": 0.05},
                                        kvstore=None))
    ckpt_dir = os.path.join(out_dir, "ckpt_kill")
    port = int(os.environ["MXTPU_CHAOS_ELASTIC_PORT"])
    g = resilience.ElasticGroup(
        rank=rank, port=port if rank == 0 else 0,
        addr=None if rank == 0 else ("127.0.0.1", port),
        sync_timeout_s=3.0)
    x0, y0 = _batch(0)
    step.ensure_built(nd.array(x0), nd.array(y0))   # compile before join
    info = g.join()
    if rejoin:
        # re-entry at the checkpoint boundary: restore last-good, then
        # enter at the group's CURRENT step (not the possibly-stale one
        # from admission — compile time passed since)
        lg = info["last_good"]
        assert lg is not None, "rejoin admitted without last-good state"
        restore_train_step(ckpt_dir, step)
        resilience.record_recovery(
            "resume", {"restored_step": lg["step"], "rank": rank,
                       "via": "elastic_rejoin"},
            step=lg["step"])
        s = g._call("info")["max_step"] + 1
    else:
        s = info["next_step"]

    def flat_params():
        return np.concatenate([np.asarray(p.data()._data).ravel()
                               for p in step.params])

    def set_params(vec):
        import jax.numpy as jnp
        off = 0
        for p in step.params:
            n = int(np.prod(p.data().shape))
            p._data._data = jnp.asarray(
                vec[off:off + n].reshape(p.data().shape), jnp.float32)
            off += n

    losses = []
    departed_seen = rejoined_seen = False
    joint_rounds = 0
    hard_cap = STEPS + 200
    while s <= hard_cap:
        x, y = _batch(1000 * rank + s)   # each rank its own data shard
        loss = float(step(nd.array(x), nd.array(y)))
        if rank == 1 and not rejoin and s == KILL_STEP:
            os.kill(os.getpid(), signal.SIGKILL)   # mid-step hard death
        try:
            mean, sync_info = g.sync(s, flat_params())
        except resilience.GroupClosed:
            break
        if sync_info["membership_changed"] and sync_info["departed"]:
            # survivors re-form at the smaller world size and roll back
            # to last-good so every survivor restarts from the same
            # state (the departed rank's half-step dies with it)
            departed_seen = True
            lg = sync_info["last_good"]
            if lg is not None:
                restore_train_step(ckpt_dir, step)
            resilience.record_recovery(
                "rollback",
                {"reason": "rank_departed", "rank": rank,
                 "departed": sync_info["departed"],
                 "to_step": (lg or {}).get("step"),
                 "from_step": s, "steps_lost":
                     max(0, s - ((lg or {}).get("step") or 0))},
                step=s)
            s += 1
            continue
        if sync_info["membership_changed"] and sync_info["joined"] \
                and departed_seen:
            # only a join AFTER the departure is the re-join this
            # scenario proves (the initial join can also arrive through
            # the boundary path when rank 1 starts a beat late)
            rejoined_seen = True
        set_params(np.asarray(mean, np.float32))
        losses.append(loss)
        mon.step_end(loss=loss)
        if rank == 0 and s % CKPT_EVERY == 0:
            path = save_train_step(ckpt_dir, step, step_num=s)
            g.report_checkpoint(s, path)
        if rejoin:
            joint_rounds += 1
            if joint_rounds >= 4:
                break                  # drained after proving the rejoin
        elif rank == 0 and s >= STEPS:
            # rank 0 finishes only once the whole story happened: the
            # departure was observed AND the relaunched rank re-joined
            # and ran a couple of joint rounds (else keep the group
            # open, up to the hard cap)
            if not departed_seen or rejoined_seen:
                if rejoined_seen:
                    joint_rounds += 1
                if not departed_seen or joint_rounds >= 3:
                    break
        elif rank != 0 and s >= STEPS:
            break
        time.sleep(sleep_s)
        s += 1
    g.leave()
    _finish(tag, mon, {"losses": _loss_trend(losses), "rank": rank,
                       "rejoin_observed": rejoined_seen,
                       "departure_observed": departed_seen,
                       "last_ckpt": latest_step(ckpt_dir)})


# ---------------------------------------------------------------------------
# parent
# ---------------------------------------------------------------------------

def _free_port() -> int:
    base = 24000 + (os.getpid() * 137) % 500
    for off in range(1000):
        port = 24000 + (base - 24000 + off) % 1000
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", port))
        except OSError:
            continue
        finally:
            s.close()
        return port
    raise RuntimeError("no free elastic port in 24000-24999")


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _spawn(args, env, timeout=WORKER_TIMEOUT_S, ok_codes=(0,)):
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=_REPO)
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        p.kill()
        out, err = p.communicate()
        raise RuntimeError(f"worker {args} timed out\nstderr:{err[-2000:]}")
    if p.returncode not in ok_codes:
        raise RuntimeError(f"worker {args} rc={p.returncode} not in "
                           f"{ok_codes}\nstdout:{out}\n"
                           f"stderr:{err[-3000:]}")
    return p.returncode, out, err


def _parse_chaos(out):
    docs = [json.loads(ln[len("CHAOS "):]) for ln in out.splitlines()
            if ln.startswith("CHAOS ")]
    return docs[-1] if docs else None


def _corrupt_latest(ckpt_dir):
    """Bit-flip the largest payload file of the NEWEST checkpoint —
    manifest untouched, so the digests must catch it."""
    from glob import glob
    steps = sorted(glob(os.path.join(ckpt_dir, "step_*")))
    victim_dir = steps[-1]
    best, best_size = None, -1
    for root, _dirs, files in os.walk(victim_dir):
        for f in files:
            if f == "manifest.json":
                continue
            p = os.path.join(root, f)
            if os.path.getsize(p) > best_size:
                best, best_size = p, os.path.getsize(p)
    with open(best, "r+b") as f:
        f.seek(best_size // 2)
        b = f.read(1) or b"\0"
        f.seek(best_size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return victim_dir, best


class Checker:
    def __init__(self):
        self.failures = []

    def check(self, cond, msg):
        if not cond:
            self.failures.append(msg)
        return cond

    def three_surfaces(self, doc, counter_keys, flight_names,
                       event_names, what):
        """The acceptance contract: the recovery must be visible on
        counters AND flight AND events."""
        c = doc["counters"]
        self.check(any(c.get(f"resilience/{k}", 0) >= 1
                       for k in counter_keys),
                   f"{what}: no recovery counter among {counter_keys}: "
                   f"{ {k: v for k, v in c.items() if 'resilience' in k} }")
        try:
            with open(doc["flight_file"]) as f:
                fl = json.load(f)
            names = {e.get("name") for e in fl.get("events", [])
                     if e.get("kind") == "resilience"}
        except (OSError, ValueError) as e:
            names = set()
            self.failures.append(f"{what}: unreadable flight dump: {e}")
        self.check(names & set(flight_names),
                   f"{what}: no {flight_names} breadcrumb in flight ring "
                   f"(saw {sorted(names)})")
        ev_names = set()
        try:
            with open(doc["events_file"]) as f:
                for ln in f:
                    if ln.strip():
                        ev_names.add(json.loads(ln).get("name"))
        except (OSError, ValueError) as e:
            self.failures.append(f"{what}: unreadable event log: {e}")
        self.check(ev_names & set(event_names),
                   f"{what}: no {event_names} record in events "
                   f"(saw {sorted(n for n in ev_names if n and 'resil' in n)})")

    def loss_decreased(self, doc, what):
        tr = doc.get("losses") or {}
        self.check(tr.get("decreased"),
                   f"{what}: loss did not decrease through the fault "
                   f"({tr})")


def run_scenarios(scenarios):
    out_dir = os.environ.get("MXTPU_CHAOS_OUT", "/tmp/mxtpu_chaos")
    import shutil
    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["MXTPU_CHAOS_OUT"] = out_dir
    env.setdefault("MXTPU_RUN_ID", f"chaos-{int(time.time())}")
    ck = Checker()
    tc = _load_tool("trace_check")
    md = _load_tool("mxdiag")
    summary = {}
    event_files = []

    if "nan" in scenarios:
        print(f"chaos[nan]: poison batch at index {NAN_BATCH}",
              flush=True)
        _, out, _ = _spawn(["nan"], env)
        doc = _parse_chaos(out)
        ck.check(doc is not None, "nan: no CHAOS report") and (
            ck.three_surfaces(doc, ["resilience.rollbacks"],
                              ["rollback"], ["resilience.rollback"],
                              "nan"),
            ck.loss_decreased(doc, "nan"),
            event_files.append(doc["events_file"]))
        if doc:
            summary["nan"] = {"rollbacks": doc["counters"].get(
                "resilience/resilience.rollbacks"),
                "losses": doc["losses"]}

    if "torn" in scenarios:
        print("chaos[torn]: train, tear newest checkpoint, restart",
              flush=True)
        _, out1, _ = _spawn(["torn", "1"], env)
        doc1 = _parse_chaos(out1)
        doc2 = None
        # gate phase 2 on the precondition so a failed phase 1 surfaces
        # as the curated verdict, not a TypeError on doc1[...]
        if ck.check(doc1 is not None and len(doc1["ckpt_steps"]) >= 2,
                    f"torn: phase 1 left <2 checkpoints "
                    f"({doc1 and doc1['ckpt_steps']}) — nothing to fall "
                    f"back to"):
            victim, vfile = _corrupt_latest(doc1["ckpt_dir"])
            print(f"chaos[torn]: corrupted {vfile}", flush=True)
            _, out2, _ = _spawn(["torn", "2"], env)
            doc2 = _parse_chaos(out2)
            ck.check(doc2 is not None, "torn: no phase-2 CHAOS report")
        if doc2:
            c = doc2["counters"]
            ck.check(c.get("resilience/resilience.corrupt_checkpoints",
                           0) >= 1,
                     f"torn: corrupt checkpoint not detected: {c}")
            ck.three_surfaces(doc2, ["resilience.resumes"],
                              ["resume"], ["resilience.resume"], "torn")
            ck.loss_decreased(doc2, "torn")
            event_files.append(doc2["events_file"])
            summary["torn"] = {
                "corrupt_detected": c.get(
                    "resilience/resilience.corrupt_checkpoints"),
                "resumes": c.get("resilience/resilience.resumes"),
                "losses": doc2["losses"]}

    if "freeze" in scenarios:
        print(f"chaos[freeze]: source wedges at batch {FREEZE_BATCH}; "
              f"stall watchdog must fire and exit 96", flush=True)
        # rc 0 is "watchdog never fired" — a CURATED failure below, not
        # a worker crash, so it must get past _spawn's rc gate
        rc, out1, err1 = _spawn(["freeze", "1"], env,
                                ok_codes=(0, 96))
        doc2 = None
        if ck.check(rc == 96,
                    f"freeze: phase 1 exited {rc}, wanted "
                    f"RESTART_EXIT_CODE 96"):
            _, out2, _ = _spawn(["freeze", "2"], env)
            doc2 = _parse_chaos(out2)
            ck.check(doc2 is not None, "freeze: no phase-2 CHAOS report")
        if doc2:
            ck.three_surfaces(doc2, ["resilience.resumes"],
                              ["resume"], ["resilience.resume"],
                              "freeze")
            ck.loss_decreased(doc2, "freeze")
            event_files.append(doc2["events_file"])
            # phase 1's stall escalation left its own trail
            ev1 = os.path.join(out_dir, "events_freeze1.jsonl")
            names = set()
            if os.path.exists(ev1):
                with open(ev1) as f:
                    names = {json.loads(ln).get("name") for ln in f
                             if ln.strip()}
            ck.check("resilience.restart_requested" in names,
                     f"freeze: no restart_requested event in phase 1 "
                     f"({sorted(n for n in names if n)})")
            event_files.append(ev1)
            summary["freeze"] = {
                "resumes": doc2["counters"].get(
                    "resilience/resilience.resumes"),
                "losses": doc2["losses"]}

    if "kill" in scenarios:
        port = _free_port()
        kenv = dict(env, MXTPU_CHAOS_ELASTIC_PORT=str(port))
        print(f"chaos[kill]: 2-rank elastic group on :{port}; rank 1 "
              f"SIGKILLs itself mid-step {KILL_STEP}", flush=True)
        p0 = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "kill", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=kenv, cwd=_REPO)
        time.sleep(1.0)
        p1 = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "kill", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=kenv, cwd=_REPO)
        p1.wait(timeout=WORKER_TIMEOUT_S)
        ck.check(p1.returncode == -signal.SIGKILL,
                 f"kill: rank 1 exited {p1.returncode}, wanted SIGKILL")
        # the survivor is re-forming; give it a beat, then relaunch
        # rank 1 to prove re-join at the checkpoint boundary
        time.sleep(2.0)
        try:
            rc1b, out1b, err1b = _spawn(["kill", "1", "--rejoin"], kenv)
        except RuntimeError as e:
            ck.check(False, f"kill: rejoin worker failed: {e}")
            out1b = ""
            p0.kill()
        out0, err0 = p0.communicate(timeout=WORKER_TIMEOUT_S)
        ck.check(p0.returncode == 0,
                 f"kill: rank 0 rc={p0.returncode}\n"
                 f"stderr:{err0[-2000:]}")
        doc0 = _parse_chaos(out0)
        doc1b = _parse_chaos(out1b)
        ck.check(doc0 is not None, "kill: no rank-0 CHAOS report")
        if doc0:
            c = doc0["counters"]
            ck.check(c.get("resilience/resilience.rank_departures",
                           0) >= 1,
                     f"kill: rank 0 never observed the departure: {c}")
            ck.check(c.get("resilience/resilience.rank_joins", 0) >= 1,
                     f"kill: rank 0 never observed the re-join: {c}")
            ck.three_surfaces(
                doc0, ["resilience.recoveries_total"],
                ["rank_departed", "rollback"],
                ["resilience.rank_departed", "resilience.rollback"],
                "kill")
            ck.loss_decreased(doc0, "kill")
            ck.check(doc0.get("departure_observed"),
                     "kill: rank 0 reports no departure observed")
            ck.check(doc0.get("rejoin_observed"),
                     "kill: rank 0 reports no re-join observed")
            event_files.append(doc0["events_file"])
        if doc1b:
            event_files.append(doc1b["events_file"])
            summary["kill"] = {
                "departures": doc0 and doc0["counters"].get(
                    "resilience/resilience.rank_departures"),
                "joins": doc0 and doc0["counters"].get(
                    "resilience/resilience.rank_joins"),
                "losses": doc0 and doc0["losses"],
                "rejoin_observed": doc0 and doc0.get("rejoin_observed")}

    # merged timeline: every scenario's events interleave into one
    # validated stream, and the recovery renderer must accept it
    artifact_errors = []
    event_files = [p for p in event_files if p and os.path.exists(p)]
    for p in event_files:
        artifact_errors += tc.check_events_jsonl(p)
    merged_path = os.path.join(out_dir, "merged.jsonl")
    merged = md.merge_timelines(event_files, out_path=merged_path)
    artifact_errors += tc.check_events_jsonl(merged_path)
    ck.check(not artifact_errors,
             f"artifact validation: {artifact_errors[:5]}")
    recover_rc = md.print_recover(merged)
    ck.check(recover_rc == 0,
             f"mxdiag recover flagged the merged timeline (rc="
             f"{recover_rc})")

    if ck.failures:
        for f in ck.failures:
            print(f"chaos: FAIL: {f}", file=sys.stderr)
        return 1
    summary["merged_records"] = len(merged)
    summary["merged_file"] = merged_path
    print("CHAOS_OK " + json.dumps(summary), flush=True)
    return 0


def main() -> int:
    scen = "all"
    argv = sys.argv[1:]
    if argv and argv[0] == "--scenario":
        scen = argv[1]
    scenarios = ("nan", "torn", "freeze", "kill") if scen == "all" \
        else tuple(scen.split(","))
    return run_scenarios(scenarios)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("XLA_FLAGS", None)
        sys.path.insert(0, _REPO)
        which = sys.argv[2]
        if which == "nan":
            worker_nan()
        elif which == "torn":
            worker_torn(int(sys.argv[3]))
        elif which == "freeze":
            worker_freeze(int(sys.argv[3]))
        elif which == "kill":
            worker_kill(int(sys.argv[3]),
                        rejoin="--rejoin" in sys.argv)
        else:
            raise SystemExit(f"unknown worker {which!r}")
        sys.exit(0)
    sys.exit(main())
