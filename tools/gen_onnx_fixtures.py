#!/usr/bin/env python
"""Generate the committed ONNX golden fixtures (VERDICT r4 #9).

The fixtures freeze the exporter's WIRE FORMAT: tests re-export the same
deterministic models and assert byte-equality against these files, so a
refactor that silently changes the serialized format fails loudly even
though our own importer (which would share the bug) still round-trips.
An onnxruntime-gated test validates the same bytes against a foreign
parser wherever that package exists (not in this image).

Run from the repo root:  python tools/gen_onnx_fixtures.py
"""
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

FIXDIR = os.path.join(ROOT, "tests", "fixtures", "onnx")


def _reset_naming():
    """Byte-determinism needs deterministic auto-names: reset the gluon
    block NameManager and the symbol auto-name counter so fixture bytes
    don't depend on what else ran earlier in the process (pytest order)."""
    from incubator_mxnet_tpu.base import NameManager
    from incubator_mxnet_tpu import symbol as S
    NameManager._tls.nm = NameManager()
    S._NAME_COUNTER.clear()


def build_lenet():
    """Deterministic LeNet-5 (models/lenet) traced to a symbol graph."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import get_model
    from incubator_mxnet_tpu.gluon.symbolize import trace_symbol

    _reset_naming()
    mx.random.seed(1234)
    np.random.seed(1234)
    net = get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.array(np.zeros((1, 1, 28, 28), np.float32)))  # deferred init
    sym, args, aux = trace_symbol(net, "data")
    return sym, {**args, **aux}, (2, 1, 28, 28)


def build_tiny_transformer():
    """Deterministic 1-layer TransformerLM (causal attention, LayerNorm,
    tied head) — the transformer-family wire format."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import TransformerLM
    from incubator_mxnet_tpu.gluon.symbolize import trace_symbol

    _reset_naming()
    mx.random.seed(4321)
    np.random.seed(4321)
    net = TransformerLM(vocab_size=17, num_layers=1, units=16,
                        hidden_size=32, num_heads=2, max_length=8)
    net.initialize(init=mx.init.Xavier())
    sym, args, aux = trace_symbol(net, "data")
    return sym, {**args, **aux}, (1, 6)


BUILDERS = {"lenet": build_lenet,
            "tiny_transformer": build_tiny_transformer}


def export_bytes(name):
    from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet
    sym, params, shape = BUILDERS[name]()
    return onnx_mxnet.export_model(sym, params, [shape],
                                   model_name=f"fixture_{name}")


def main():
    os.makedirs(FIXDIR, exist_ok=True)
    for name in BUILDERS:
        data = export_bytes(name)
        path = os.path.join(FIXDIR, f"{name}.onnx")
        with open(path, "wb") as f:
            f.write(data)
        print(f"wrote {path} ({len(data)} bytes)")


if __name__ == "__main__":
    main()
