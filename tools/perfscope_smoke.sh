#!/bin/bash
# Tier-1 perfscope smoke: 50 lenet train steps ON CPU through bench.py
# with roofline cost capture + step-time decomposition armed, then
# assert from the BENCH json that
#   * extra.perfscope is present: decomposition components all there and
#     summing to within 15% of measured step_ms (the acceptance bound),
#   * at least one compiled hot program carries a roofline verdict from
#     the known taxonomy (the fused train step must be among them),
#   * the perfscope.* counter families validate (trace_check),
# and that the regression gate behaves:
#   * perf_regress self-vs-self exits 0,
#   * perf_regress vs a synthetically 20%-degraded copy exits nonzero,
#   * perf_regress SKIPS an env_failure artifact instead of reading it
#     as a 100% regression.
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_perfscope_smoke_bench.json}
LOG=/tmp/mxtpu_perfscope_smoke.log

echo "perfscope_smoke: 50 lenet steps on CPU with perfscope armed"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 \
  BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 \
  BENCH_TRACE_FILE=/tmp/mxtpu_perfscope_smoke_trace.json \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "perfscope_smoke: bench.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
ps = (doc.get("extra") or {}).get("perfscope")
assert isinstance(ps, dict), "no extra.perfscope in BENCH json"
d = ps.get("decomposition")
assert isinstance(d, dict), "no step-time decomposition"
comps = ("device_compute_ms", "collective_ms", "input_wait_ms",
         "host_gap_ms", "other_ms")
for c in comps:
    assert isinstance(d.get(c), (int, float)) and d[c] >= 0, \
        f"component {c} missing/invalid: {d.get(c)!r}"
step = d["step_ms"]
total = sum(d[c] for c in comps)
off = abs(total - step) / step
assert off <= 0.15, \
    f"components sum {total:.3f} vs step_ms {step:.3f}: {off:.1%} > 15%"
progs = ps.get("programs") or []
verdicts = {p["name"]: p["verdict"] for p in progs}
assert any(n.startswith("fused_step") for n in verdicts), \
    f"no fused_step program analyzed (got {sorted(verdicts)})"
allowed = {"compute_bound", "hbm_bound", "trivial", "unknown"}
assert all(v in allowed for v in verdicts.values()), verdicts
c = (doc.get("extra") or {}).get("counters") or {}
for name in ("perfscope/perfscope.programs_analyzed",
             "perfscope/perfscope.step_ms",
             "perfscope/perfscope.device_compute_ms"):
    assert name in c, f"counter {name} missing from BENCH json"
print(f"perfscope_smoke: decomposition OK (step_ms={step:.2f}, "
      f"coverage={d.get('coverage')}, "
      f"verdicts={sorted(set(verdicts.values()))})")
EOF

# schema-check the BENCH json (perfscope section + counter families)
python tools/trace_check.py "$OUT" || exit 1

# regression gate: self-comparison must pass ...
python tools/perf_regress.py "$OUT" "$OUT" > /dev/null \
  || { echo "perfscope_smoke: perf_regress failed self-vs-self"; exit 1; }
# ... a 20% img/s+MFU degradation must fail ...
python - "$OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
doc["value"] = round(doc["value"] * 0.8, 2)
extra = doc.setdefault("extra", {})
if isinstance(extra.get("mfu"), (int, float)):
    extra["mfu"] = round(extra["mfu"] * 0.8, 6)
json.dump(doc, open("/tmp/mxtpu_perfscope_degraded.json", "w"))
json.dump({"metric": doc["metric"], "value": 0.0, "unit": doc["unit"],
           "status": "env_failure", "error": "injected: wedged tunnel"},
          open("/tmp/mxtpu_perfscope_envfail.json", "w"))
EOF
if python tools/perf_regress.py "$OUT" /tmp/mxtpu_perfscope_degraded.json \
    > /dev/null; then
  echo "perfscope_smoke: perf_regress MISSED a 20% regression"; exit 1
fi
# ... and an env_failure candidate is SKIPPED (exit 0), not flagged.
python tools/perf_regress.py "$OUT" /tmp/mxtpu_perfscope_envfail.json \
  > /dev/null \
  || { echo "perfscope_smoke: perf_regress did not skip env_failure"; exit 1; }

echo "perfscope_smoke: attribution + regression gate validate"
