#!/bin/bash
# Tier-1 fleet smoke (CPU-only, no TPU, no tunnel): proves the three
# mxtpu.fleet acceptance claims end to end on a 2-replica CPU lenet:
#   (a) continuous batching is LIVE under load — requests admitted
#       while a dispatch is in flight carry the `slotted` servescope
#       mark in the mxtpu.events/1 stream, and
#       serving.slotted_admissions counts them;
#   (b) a draining hot-swap deploy (drain -> swap -> readmit, every
#       replica) drops or errors ZERO requests under concurrent load;
#   (c) a 2-replica spawned fleet behind the Router sustains a
#       serve_load ramp, emits a trace_check-valid BENCH json with a
#       populated extra.fleet section, replica N+1's warmup hits the
#       shared on-disk AOT compile cache, and perf_regress.py accepts
#       the artifact (both the real fleet-vs-fleet gates and the
#       metric-mismatch path vs a differently-sized fleet).
# Replica SCALING is a multi-core claim: on a multi-core host this
# script asserts fleet-2 beats fleet-1 outright; on a 1..3-core host
# (where two replicas time-slice one core and batch fission makes the
# fleet structurally slower) it asserts the fleet stays within budget
# of the single-replica baseline and explains why — see docs/serving.md.
set -u
cd "$(dirname "$0")/.." || exit 1

SMOKE_DIR=${MXTPU_FLEET_SMOKE_DIR:-/tmp/mxtpu_fleet_smoke}
rm -rf "$SMOKE_DIR"; mkdir -p "$SMOKE_DIR"
export JAX_PLATFORMS=cpu

# ---- part 1: continuous batching + zero-drop deploy (in-process) ----
echo "fleet_smoke: in-process 2-replica lenet — slotted admissions +"
echo "fleet_smoke: draining hot-swap under concurrent load"
MXTPU_FLEET_SMOKE_DIR="$SMOKE_DIR" \
timeout -k 10 900 python - <<'EOF' || exit 1
import json, os, threading, time, urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler as prof
from incubator_mxnet_tpu import servescope
from incubator_mxnet_tpu.fleet import CompileCache, ReplicaSet, Router
from incubator_mxnet_tpu.healthmon import events as hm_events
from incubator_mxnet_tpu.models import get_model

smoke_dir = os.environ["MXTPU_FLEET_SMOKE_DIR"]
events_path = os.path.join(smoke_dir, "inproc_events.jsonl")
servescope.enable()
hm_events.open_log(events_path, run_id="fleet-smoke-inproc", rank=0)


def factory(compile_cache=None):
    net = get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    return net.freeze(input_shape=(1, 28, 28), batch_buckets=(1, 4, 8),
                      compile_cache=compile_cache)


cache = CompileCache(os.path.join(smoke_dir, "inproc_cache"))
rset = ReplicaSet(factory, n=2, batcher="continuous", compile_cache=cache)
rset.start()
router = Router(rset, poll_interval_s=10.0)
host, port = router.start()
url = f"http://{host}:{port}/predict"
body = json.dumps({"data": np.zeros((1, 28, 28)).tolist()}).encode()

stop = threading.Event()
ok, failures = [], []


def client():
    while not stop.is_set():
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                doc = json.loads(r.read())
                (ok if r.status == 200 else failures).append(doc)
        except Exception as e:  # noqa: BLE001
            failures.append(repr(e))


threads = [threading.Thread(target=client) for _ in range(6)]
for t in threads:
    t.start()
time.sleep(1.0)                       # sustained load before the deploy
router.deploy(factory, compile_cache=cache, timeout=60.0)
time.sleep(0.5)                       # and after it
stop.set()
for t in threads:
    t.join()
router.stop()
rset.stop(drain=True)
hm_events.close_log()
servescope.disable()

c = prof.counters()
assert not failures, f"deploy dropped/errored requests: {failures[:3]}"
assert len(ok) > 50, f"load never ramped: {len(ok)} responses"
slotted = c.get("serving/serving.slotted_admissions", 0)
assert slotted > 0, "no mid-flight admissions under sustained load"
assert c.get("fleet/fleet.drains", 0) == 2, c
assert c.get("fleet/fleet.swaps", 0) == 2, c
assert c.get("fleet/fleet.readmits", 0) == 2, c
hits = c.get("fleet/fleet.compile_cache_hits", 0)
assert hits > 0, "replica/deploy warmups never hit the shared cache"

# the slotted mark must be visible PER REQUEST in the event stream
with open(events_path) as f:
    recs = [json.loads(ln) for ln in f if ln.strip()]
span_recs = [r for r in recs if r.get("name") == "serving.request"]
tagged = [r for r in span_recs
          if (r.get("args") or {}).get("slotted") is True]
assert tagged, "no serving.request event carries the slotted mark"
print(f"fleet_smoke: in-process OK — {len(ok)} responses, 0 drops, "
      f"{slotted} slotted admissions ({len(tagged)} tagged events), "
      f"2 drains/swaps/readmits, {hits} cache hits")
EOF

# the in-process event log must be a valid mxtpu.events/1 stream
python tools/trace_check.py "$SMOKE_DIR/inproc_events.jsonl" || exit 1

# ---- part 2: spawned 2-replica fleet ramp vs 1-replica baseline ----
echo "fleet_smoke: spawned-worker serve_load ramp (fleet 1 then fleet 2)"
FLEET1="$SMOKE_DIR/fleet1.json"
FLEET2="$SMOKE_DIR/fleet2.json"
CACHE="$SMOKE_DIR/aot_cache"

timeout -k 10 900 python tools/serve_load.py --fleet 1 \
  --ramp 4,8,16 --level-requests 96 --fleet-cache "$CACHE" \
  --out "$FLEET1" --events "$SMOKE_DIR/fleet1_events.jsonl" \
  > "$SMOKE_DIR/fleet1.log" 2>&1
rc=$?
if [ "$rc" != "0" ]; then
  echo "fleet_smoke: fleet-1 serve_load failed rc=$rc"
  tail -30 "$SMOKE_DIR/fleet1.log"; exit 1
fi
timeout -k 10 900 python tools/serve_load.py --fleet 2 \
  --ramp 4,8,16 --level-requests 96 --fleet-cache "$CACHE" \
  --out "$FLEET2" --events "$SMOKE_DIR/fleet2_events.jsonl" \
  > "$SMOKE_DIR/fleet2.log" 2>&1
rc=$?
if [ "$rc" != "0" ]; then
  echo "fleet_smoke: fleet-2 serve_load failed rc=$rc"
  tail -30 "$SMOKE_DIR/fleet2.log"; exit 1
fi

# both artifacts + both event logs must validate structurally
python tools/trace_check.py "$FLEET1" "$FLEET2" \
  "$SMOKE_DIR/fleet1_events.jsonl" "$SMOKE_DIR/fleet2_events.jsonl" \
  || exit 1

# fleet semantics: balanced dispatch, clean router accounting, shared
# cache hit on replica N+1's warmup, live continuous batching, and the
# core-aware throughput claim
python - "$FLEET1" "$FLEET2" <<'EOF' || exit 1
import json, os, sys

f1 = json.load(open(sys.argv[1]))
f2 = json.load(open(sys.argv[2]))
q1, q2 = f1["value"], f2["value"]
fl = (f2.get("extra") or {}).get("fleet") or {}
assert fl.get("replicas") == 2, f"extra.fleet broken: {fl}"
rows = fl["per_replica"]
assert all(r["requests"] > 0 for r in rows), \
    f"a replica never served: {rows}"
assert fl.get("routed_errors", 0) == 0, fl
assert fl.get("no_replica_available", 0) == 0, fl
cc = fl.get("compile_cache") or {}
assert cc.get("hits", 0) > 0, \
    f"replica N+1 warmup missed the shared AOT cache: {cc}"
sv = (f2.get("extra") or {}).get("serving") or {}
assert sv.get("slotted_admissions", 0) > 0, \
    "continuous batching idle: no slotted admissions in the fleet"
cores = os.cpu_count() or 1
if cores >= 4:
    assert q2 > q1, \
        f"{cores} cores but fleet-2 knee {q2} <= fleet-1 knee {q1}"
    print(f"fleet_smoke: fleet-2 out-scales fleet-1 "
          f"({q2:.0f} > {q1:.0f} qps at knee, {cores} cores)")
else:
    # two replicas time-slicing <4 cores cannot win (batch fission:
    # each replica sees half the arrival rate, so batches shrink and
    # per-batch overhead doubles) — assert the fleet machinery itself
    # costs a bounded amount instead of a throughput win it cannot
    # physically deliver here
    assert q2 >= 0.55 * q1, \
        f"fleet-2 knee {q2} < 55% of fleet-1 knee {q1}: routing " \
        f"overhead regression"
    print(f"fleet_smoke: {cores} core(s) — scaling unprovable here; "
          f"fleet-2 within budget ({q2:.0f} vs {q1:.0f} qps at knee)")
print(f"fleet_smoke: fleet artifacts OK — dispatch "
      f"{fl['dispatch_counts']}, imbalance "
      f"{fl['dispatch_imbalance']:.2f}, {cc.get('hits')} cache hits, "
      f"{sv.get('slotted_admissions')} slotted admissions")
EOF

# regression gates: fleet-vs-fleet exercises the real value/p99 gates;
# fleet-1 vs fleet-2 carry DIFFERENT metric names by design, so the
# both-sides contract must conclude "nothing comparable" (exit 0), not
# invent a 2x-replicas "regression"
python tools/perf_regress.py "$FLEET2" "$FLEET2" || {
  echo "fleet_smoke: perf_regress rejected fleet-2 vs itself"; exit 1; }
python tools/perf_regress.py "$FLEET1" "$FLEET2" || {
  echo "fleet_smoke: perf_regress must accept a fleet-size change as"
  echo "fleet_smoke: incomparable (distinct metric), not a regression"
  exit 1; }

# the renderer must be able to tell the story from the artifact alone
python tools/mxdiag.py fleet "$FLEET2" > "$SMOKE_DIR/mxdiag_fleet.txt" \
  || exit 1
grep -q "replica1" "$SMOKE_DIR/mxdiag_fleet.txt" || {
  echo "fleet_smoke: mxdiag fleet lost the replica table"; exit 1; }

echo "fleet_smoke: all fleet artifacts validate"
