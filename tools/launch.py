#!/usr/bin/env python
"""Multi-worker job launcher (parity: the reference's tools/launch.py,
which starts DMLC/ps-lite workers over ssh with DMLC_* env vars).

TPU-native contract: every worker runs the SAME SPMD program and calls
``mx.distributed.init()``, which reads the MXTPU_COORDINATOR /
MXTPU_NUM_PROCESSES / MXTPU_PROCESS_ID variables this launcher sets —
the analogue of the reference's DMLC_PS_ROOT_URI / DMLC_NUM_WORKER /
DMLC_WORKER_ID. After init, ``jax.devices()`` spans the cluster and one
``Mesh`` provides the collectives (no scheduler/server processes: the
reference's ps-lite topology has no TPU analogue).

Local mode (default) spawns -n worker processes on this machine —
useful for multi-process testing and for machines exposing several
accelerator processes. With -H HOSTFILE, workers start over ssh, one
per host line (passwordless ssh assumed, like the reference launcher).

Examples:
  python tools/launch.py -n 4 python train.py --epochs 1
  python tools/launch.py -n 8 -H hosts.txt --env FOO=1 python train.py
"""
import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _stream(proc, rank, out=sys.stdout):
    """Prefix each worker line with its rank (reference launcher does the
    same so interleaved logs stay attributable)."""
    for line in proc.stdout:
        out.write(f"[{rank}] {line}")
        out.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="launch N distributed workers (local or ssh)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-H", "--hostfile",
                    help="file with one host per line -> ssh mode")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of rank 0 (default: this host, a free "
                         "port)")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env for every worker")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="the training command (every worker runs it)")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    command = args.command[1:] if args.command[0] == "--" else args.command
    n = args.num_workers

    extra = {}
    for kv in args.env:
        if "=" not in kv:
            ap.error(f"--env expects K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        extra[k] = v

    hosts = None
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h for h in (ln.strip() for ln in f)
                     if h and not h.startswith("#")]
        if len(hosts) < n:
            sys.exit(f"hostfile has {len(hosts)} hosts < -n {n}")

    if args.coordinator:
        coordinator = args.coordinator
    elif hosts:
        # the port must be free on hosts[0]: probe candidates there over
        # ssh (a one-line bind test) so a busy port surfaces here as a
        # retried candidate, not later as every worker's opaque rendezvous
        # failure; if the probe itself can't run, fall back to random
        import random
        port = None
        probes_ran = 0
        for cand in random.sample(range(20000, 60000), 4):
            try:
                r = subprocess.run(
                    ["ssh", "-o", "BatchMode=yes", hosts[0],
                     f"python3 -c \"import socket; s=socket.socket(); "
                     f"s.bind(('', {cand})); s.close()\""],
                    capture_output=True, timeout=15)
            except Exception:  # ssh missing/unreachable: can't probe
                break
            probes_ran += 1
            if r.returncode == 0:
                port = cand
                break
            if r.returncode in (255, 127):
                # 255 = ssh transport/auth failure, 127 = no python3 on
                # the host: retrying other ports can never succeed, and
                # "port busy" would send the operator down the wrong path
                print(f"launch: cannot probe ports on {hosts[0]} "
                      f"(ssh/python3 failure rc={r.returncode}: "
                      f"{r.stderr.decode(errors='replace').strip()[:120]})",
                      file=sys.stderr)
                break
            print(f"launch: port {cand} busy on {hosts[0]}; retrying",
                  file=sys.stderr)
        if port is None:
            port = random.randint(20000, 59999)
            why = (f"all {probes_ran} probed candidates were busy/refused"
                   if probes_ran == 4 else
                   f"probing stopped after {probes_ran} attempts")
            print(f"launch: {why} on {hosts[0]}; using unverified port "
                  f"{port}", file=sys.stderr)
        coordinator = f"{hosts[0]}:{port}"
        print(f"launch: coordinator {coordinator} (pass --coordinator to "
              "pin one known-free on that host)", file=sys.stderr)
    else:
        coordinator = f"127.0.0.1:{_free_port()}"

    # one correlation id for the whole run: every rank's healthmon event
    # log / flight dump carries it, so `mxdiag merge` can interleave them
    # (the launcher is the natural place to mint it — same role as the
    # reference tracker's job id)
    import time as _time
    run_id = os.environ.get(
        "MXTPU_RUN_ID", f"launch-{int(_time.time())}-{os.getpid():x}")

    procs = []
    threads = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(extra)
        env.update({"MXTPU_COORDINATOR": coordinator,
                    "MXTPU_NUM_PROCESSES": str(n),
                    "MXTPU_PROCESS_ID": str(rank),
                    "MXTPU_RUN_ID": run_id})
        if hosts:
            # reference-style ssh fanout: env rides the remote command line
            envs = " ".join(f"{k}={shlex.quote(v)}"
                            for k, v in sorted(env.items())
                            if k.startswith("MXTPU_") or k in extra)
            remote = f"cd {shlex.quote(os.getcwd())} && {envs} " + " ".join(
                shlex.quote(c) for c in command)
            # -tt allocates a pty so terminating the local ssh client
            # HUPs the remote worker too (otherwise remote pythons orphan
            # and hold their chips when a peer fails or the operator ^Cs)
            p = subprocess.Popen(["ssh", "-tt", "-o", "BatchMode=yes",
                                  hosts[rank], remote],
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
        else:
            p = subprocess.Popen(command, env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, rank), daemon=True)
        t.start()
        threads.append(t)

    def _terminate(*_):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    # fail fast: poll ALL workers — waiting in rank order would let a
    # crashed high-rank worker strand the others in their next collective
    # (holding accelerators) before the launcher ever noticed
    import time
    rc = 0
    live = set(range(n))
    while live:
        for rank in sorted(live):
            p = procs[rank]
            if p.poll() is not None:
                live.discard(rank)
                if p.returncode != 0:
                    print(f"launch: worker {rank} exited "
                          f"rc={p.returncode}; terminating the rest",
                          file=sys.stderr)
                    if hosts and not args.coordinator:
                        print("launch: if workers died in distributed "
                              f"rendezvous, the coordinator port on "
                              f"{hosts[0]} may be busy — rerun with "
                              "--coordinator host:port pinned to a "
                              "known-free port", file=sys.stderr)
                    rc = rc or p.returncode
                    _terminate()
        if live:
            time.sleep(0.2)
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
