#!/bin/bash
# Tier-1 devicescope smoke: 50 lenet train steps ON CPU through bench.py
# with a measured device-timeline capture window armed
# (BENCH_DEVICESCOPE=1), then assert from the BENCH json that
#   * extra.devicescope carries a COMPLETED window whose measured busy
#     fraction is in (0, 1],
#   * the top-K device-op table is nonempty and joined to perfscope's
#     program table (the fused train step must appear as a program),
#   * the reconciliation block is present: measured device_compute set
#     beside the probe-based analytic number, and the step budget's
#     provenance upgraded to measured(profile),
#   * the devicescope.* counter families + extra.devicescope schema
#     validate (trace_check),
#   * `mxdiag.py device` and `mxdiag.py perf` render it,
# and that the artifact-dir rotation bounds repeated runs.
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_devicescope_smoke_bench.json}
LOG=/tmp/mxtpu_devicescope_smoke.log
DSDIR=/tmp/mxtpu_devicescope_smoke_windows

rm -rf "$DSDIR"
echo "devicescope_smoke: 50 lenet steps on CPU with a capture window"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 \
  BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 BENCH_DEVICESCOPE=1 \
  MXTPU_DEVICESCOPE_DIR="$DSDIR" \
  BENCH_TRACE_FILE=/tmp/mxtpu_devicescope_smoke_trace.json \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "devicescope_smoke: bench.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
ds = (doc.get("extra") or {}).get("devicescope")
assert isinstance(ds, dict), "no extra.devicescope in BENCH json"
win = ds.get("window")
assert isinstance(win, dict), f"no completed capture window: {ds!r}"
assert win.get("complete") is True, f"window incomplete: {win!r}"
bf = ds.get("busy_fraction")
assert isinstance(bf, (int, float)) and 0.0 < bf <= 1.0, \
    f"busy fraction {bf!r} not in (0, 1]"
tops = ds.get("top_ops") or []
assert tops, "top-K device-op table is empty"
progs = {t.get("program") for t in tops}
assert any(p and p.startswith("fused_step") for p in progs), \
    f"top-K not joined to the fused train step (programs: {progs})"
gaps = ds.get("gaps") or {}
tax = gaps.get("taxonomy") or {}
assert all(isinstance(tax.get(k), (int, float))
           for k in ("input_starved_ms", "dispatch_serialized_ms",
                     "host_gap_ms")), f"gap taxonomy malformed: {tax!r}"
recon = ds.get("reconciliation")
assert isinstance(recon, dict), "no reconciliation block"
assert isinstance((recon.get("measured") or {}).get(
    "device_compute_ms"), (int, float)), recon
assert isinstance((recon.get("analytic") or {}).get(
    "device_compute_ms"), (int, float)), recon
d = ((doc.get("extra") or {}).get("perfscope") or {}).get(
    "decomposition") or {}
assert d.get("source") == "measured(profile)", \
    f"budget provenance not upgraded: {d.get('source')!r}"
c = (doc.get("extra") or {}).get("counters") or {}
for name in ("devicescope/devicescope.windows",
             "devicescope/devicescope.busy_fraction"):
    assert name in c, f"counter {name} missing from BENCH json"
print(f"devicescope_smoke: window OK (busy={bf:.1%}, "
      f"{len(tops)} top ops, drift_warning="
      f"{recon.get('drift_warning')})")
EOF

# schema-check the BENCH json (devicescope section + counter families)
python tools/trace_check.py "$OUT" || exit 1

# the renderers must handle a real artifact
python tools/mxdiag.py device "$OUT" > /dev/null \
  || { echo "devicescope_smoke: mxdiag device failed"; exit 1; }
python tools/mxdiag.py perf "$OUT" > /dev/null \
  || { echo "devicescope_smoke: mxdiag perf failed"; exit 1; }

# rotation: a second armed run must not grow the artifact dir past the
# keep bound (3 window dirs)
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=20 \
  BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 BENCH_DEVICESCOPE=1 \
  MXTPU_DEVICESCOPE_DIR="$DSDIR" BENCH_TRACE=0 \
  timeout -k 10 900 python bench.py > /tmp/mxtpu_ds_smoke2.json 2>> "$LOG" \
  || { echo "devicescope_smoke: second bench run failed"; exit 1; }
NDIRS=$(find "$DSDIR" -maxdepth 1 -name 'win_*' -type d | wc -l)
if [ "$NDIRS" -gt 3 ]; then
  echo "devicescope_smoke: rotation failed ($NDIRS window dirs > 3)"
  exit 1
fi

# the busy-fraction regression gate: self-vs-self passes, a synthetic
# 30% busy drop fails, one-sided windows are skipped (both-sides rule)
python tools/perf_regress.py "$OUT" "$OUT" > /dev/null \
  || { echo "devicescope_smoke: perf_regress failed self-vs-self"; exit 1; }
python - "$OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
ds = doc["extra"]["devicescope"]
ds["busy_fraction"] = round(ds["busy_fraction"] * 0.7, 6)
json.dump(doc, open("/tmp/mxtpu_ds_smoke_degraded.json", "w"))
del doc["extra"]["devicescope"]
json.dump(doc, open("/tmp/mxtpu_ds_smoke_nowin.json", "w"))
EOF
python tools/perf_regress.py "$OUT" /tmp/mxtpu_ds_smoke_degraded.json \
  > /dev/null 2>&1
if [ "$?" = "0" ]; then
  echo "devicescope_smoke: perf_regress missed a 30% busy-fraction drop"
  exit 1
fi
python tools/perf_regress.py /tmp/mxtpu_ds_smoke_nowin.json "$OUT" \
  > /dev/null \
  || { echo "devicescope_smoke: one-sided window must be skipped, not gated"; \
       exit 1; }

echo "devicescope_smoke: OK"
