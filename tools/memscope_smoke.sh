#!/bin/bash
# Tier-1 memscope smoke: 50 lenet train steps ON CPU through bench.py
# with memory observability armed (BENCH_MEMSCOPE=1), then assert from
# the BENCH json that
#   * extra.memscope carries a static footprint for the fused train
#     step, JOINED to its perfscope roofline row, with the closed
#     provenance taxonomy (XLA:CPU reports memory_analysis but no peak
#     field, so the peak must be "derived"),
#   * the watermark ring sampled the steady loop and stayed BOUNDED
#     (ring <= ring_limit even though samples > ring_limit),
#   * the capacity/headroom verdict is decided (host RAM is the honest
#     capacity on XLA:CPU),
#   * the memscope.* counter families + extra.memscope schema validate
#     (trace_check), `mxdiag.py mem` renders, and perf_regress flags an
#     injected 30% peak-memory growth while skipping one-sided pairs,
# then prove the SPEND side: an autotune search with an injected
# over-capacity batch candidate (MXTPU_AUTOTUNE_BATCH_CANDIDATES +
# MXTPU_MEMSCOPE_CAPACITY) must record a counted reason=memory
# pre-trial prune with ZERO subprocess trials spent on it, and the
# winner must still install from cache on the second run.
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_memscope_smoke_bench.json}
LOG=/tmp/mxtpu_memscope_smoke.log
TUNE1=/tmp/mxtpu_memscope_smoke_tune1.json
TUNE2=/tmp/mxtpu_memscope_smoke_tune2.json
CACHE=/tmp/mxtpu_memscope_smoke_cache
DSDIR=/tmp/mxtpu_memscope_smoke_windows

rm -rf "$CACHE" "$DSDIR"
: > "$LOG"

echo "memscope_smoke: 50 lenet steps on CPU with memscope armed"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 \
  BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 BENCH_TRACE=0 \
  BENCH_MEMSCOPE=1 MXTPU_MEMSCOPE_RING=16 \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "memscope_smoke: bench.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
ms = (doc.get("extra") or {}).get("memscope")
assert isinstance(ms, dict), "no extra.memscope in BENCH json"
progs = {p.get("name"): p for p in ms.get("programs") or []}
fused = next((p for n, p in progs.items()
              if n and n.startswith("fused_step")), None)
assert fused is not None, \
    f"fused train step has no static footprint (programs: {sorted(progs)})"
assert fused.get("available") is True, fused
assert fused.get("provenance") == "derived", \
    f"XLA:CPU has no peak field, expected derived, got {fused!r}"
assert isinstance(fused.get("peak_bytes"), int) \
    and fused["peak_bytes"] > 0, fused
assert "roofline" in fused, "footprint not joined to the roofline table"
wm = ms.get("watermarks") or {}
assert wm.get("ring_limit") == 16, wm.get("ring_limit")
assert wm.get("ring") <= 16, f"ring unbounded: {wm.get('ring')}"
assert wm.get("samples") >= 50, \
    f"steady loop under-sampled: {wm.get('samples')} < 50 steps"
rss = wm.get("host_rss") or {}
assert rss.get("peak"), f"no host RSS watermark on CPU: {rss!r}"
hr = ms.get("headroom") or {}
assert hr.get("verdict") in ("ok", "tight"), \
    f"headroom verdict undecided on CPU: {hr!r}"
assert (ms.get("capacity") or {}).get("source") == "host_ram", \
    ms.get("capacity")
assert ms.get("oom") is None, f"phantom OOM post-mortem: {ms['oom']!r}"
c = (doc.get("extra") or {}).get("counters") or {}
for name in ("memscope/memscope.programs_captured",
             "memscope/memscope.samples"):
    assert name in c, f"counter {name} missing from BENCH json"
print(f"memscope_smoke: footprints OK "
      f"(fused peak {fused['peak_bytes']} B [{fused['provenance']}], "
      f"ring {wm['ring']}/{wm['ring_limit']} of {wm['samples']} samples, "
      f"headroom {hr.get('headroom_fraction')})")
EOF

# schema-check the BENCH json (memscope section + counter families)
python tools/trace_check.py "$OUT" || exit 1

# the renderer must handle a real artifact
python tools/mxdiag.py mem "$OUT" > /dev/null \
  || { echo "memscope_smoke: mxdiag mem failed"; exit 1; }

# the peak-memory regression gate: self-vs-self passes, a synthetic 30%
# peak growth fails, one-sided memscope pairs are skipped (both-sides)
python tools/perf_regress.py "$OUT" "$OUT" > /dev/null \
  || { echo "memscope_smoke: perf_regress failed self-vs-self"; exit 1; }
python - "$OUT" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
wm = doc["extra"]["memscope"]["watermarks"]
for sect in ("device", "host_rss"):
    if isinstance(wm.get(sect), dict) and wm[sect].get("peak"):
        wm[sect]["peak"] = int(wm[sect]["peak"] * 1.3)
json.dump(doc, open("/tmp/mxtpu_memscope_smoke_hungry.json", "w"))
doc["extra"].pop("memscope")
json.dump(doc, open("/tmp/mxtpu_memscope_smoke_noms.json", "w"))
EOF
python tools/perf_regress.py --threshold 0.9 --busy-threshold 0.9 \
  "$OUT" /tmp/mxtpu_memscope_smoke_hungry.json > /dev/null 2>&1
if [ "$?" = "0" ]; then
  echo "memscope_smoke: perf_regress missed a 30% peak-memory growth"
  exit 1
fi
python tools/perf_regress.py --threshold 0.9 --busy-threshold 0.9 \
  /tmp/mxtpu_memscope_smoke_noms.json "$OUT" > /dev/null \
  || { echo "memscope_smoke: one-sided memscope must be skipped, not gated"; \
       exit 1; }

# ---- the SPEND side: the autotuner's memory-feasibility pruner --------
# An injected batch candidate of 65536 (1024x the baseline's 64) cannot
# fit under an 8 GiB capacity override: the linear-batch prediction
# scales the baseline's measured RSS peak far past capacity x headroom,
# so the candidate must be rejected BEFORE any subprocess is spawned.
run_tuned() {
  JAX_PLATFORMS=cpu MXTPU_AUTOTUNE=1 MXTPU_AUTOTUNE_CACHE="$CACHE" \
    MXTPU_AUTOTUNE_BUDGET=2 MXTPU_AUTOTUNE_STEPS=8 \
    MXTPU_AUTOTUNE_TRIAL_TIMEOUT=420 \
    MXTPU_AUTOTUNE_BATCH_CANDIDATES=65536 \
    MXTPU_MEMSCOPE_CAPACITY=8589934592 \
    MXTPU_DEVICESCOPE_DIR="$DSDIR" \
    BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=24 \
    BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 BENCH_PREFLIGHT=0 \
    BENCH_TRACE=0 BENCH_DEVICESCOPE=1 BENCH_MEMSCOPE=1 \
    timeout -k 10 1500 python bench.py > "$1" 2>> "$LOG"
}

echo "memscope_smoke: autotune run 1 (injected over-capacity batch)"
run_tuned "$TUNE1"
rc=$?
if [ "$rc" != "0" ]; then
  echo "memscope_smoke: tuned bench run 1 failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$TUNE1" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
at = (doc.get("extra") or {}).get("autotune")
assert isinstance(at, dict) and at.get("enabled") is True, at
assert at.get("cache_hit") is False, "run 1 must be a cache MISS"
pruned = at.get("pruned") or {}
reason = pruned.get("batch=65536")
assert isinstance(reason, str) and reason.startswith("memory:"), \
    f"over-capacity batch not pruned with reason=memory: {pruned!r}"
# zero subprocess spent: no trial row may carry the infeasible batch
for row in at.get("trial_table") or []:
    cfg = row.get("config") or {}
    assert cfg.get("batch") != 65536, \
        f"a subprocess WAS spent on the infeasible batch: {row!r}"
# counter == payload contract: the counted prunes include this one
tp = at.get("trials_pruned")
assert isinstance(tp, int) and tp >= 1, f"trials_pruned={tp!r}"
c = (doc.get("extra") or {}).get("counters") or {}
assert c.get("autotune/autotune.trials_pruned") == tp, \
    (c.get("autotune/autotune.trials_pruned"), tp)
assert "memscope/memscope.infeasible_candidates" in c, \
    "infeasible candidate not counted in the memscope family"
print(f"memscope_smoke: pruner OK (batch=65536 rejected pre-trial, "
      f"{tp} candidate(s) pruned, reason: {reason[:72]}...)")
EOF

echo "memscope_smoke: autotune run 2 (same key -> cache hit)"
run_tuned "$TUNE2"
rc=$?
if [ "$rc" != "0" ]; then
  echo "memscope_smoke: tuned bench run 2 failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$TUNE1" "$TUNE2" <<'EOF' || exit 1
import json, sys
d1 = json.load(open(sys.argv[1]))
d2 = json.load(open(sys.argv[2]))
at = (d2.get("extra") or {}).get("autotune")
assert isinstance(at, dict) and at.get("cache_hit") is True, \
    f"run 2 must be a cache HIT: {at and at.get('cache_hit')!r}"
assert at.get("trials") == 0, at.get("trials")
w1 = ((d1.get("extra") or {}).get("autotune") or {}).get("winner")
assert at.get("winner") == w1, (at.get("winner"), w1)
print("memscope_smoke: cache hit OK (winner installed, 0 trials)")
EOF

# both tuned artifacts must also validate (autotune + memscope sections)
python tools/trace_check.py "$TUNE1" "$TUNE2" || exit 1

echo "memscope_smoke: OK"
