#!/usr/bin/env python
"""BENCH regression gate: compare bench artifacts with noise-aware
thresholds and an environment-failure filter.

Why this exists: the repo's perf history mixes real measurements
(PERF.md, BENCH_r01) with artifacts of a wedged backend (BENCH_r02–r05
record a hung axon tunnel, value 0.0). A naive comparator reads those as
100% regressions and either cries wolf or — worse — adopts 0 img/s as a
baseline every later run "beats". This tool:

* **skips env-failure artifacts** — anything carrying
  ``"status": "env_failure"`` (bench.py's preflight/watchdog artifacts),
  an ``error`` field, a null ``parsed`` wrapper, or a non-positive
  value. They describe the environment, not the code;
* **compares the metrics that matter** — headline throughput
  (``value`` — for ``tools/serve_load.py`` sweeps that IS the QPS at
  the saturation knee), ``extra.mfu`` (ROADMAP item 1's regression
  metric), serving ``p99_ms`` (at the knee for serve_load artifacts,
  with the knee's position reported as context — on a discrete ramp it
  moves in whole levels, so a shift alone is a note, not a verdict),
  the per-step collective payload
  (``extra.commscope.step.bytes`` — a LAYOUT regression: a new
  accidental reshard inflates in-program collective bytes even when
  the CPU-bench wall time barely moves), and the MEASURED device busy
  fraction (``extra.devicescope.busy_fraction`` — the ground-truth
  utilization a devicescope capture window measured; a drop means the
  chip got idler even if wall-clock noise hides it) — relative, per
  metric, only when both sides carry the number. The busy gate follows
  the same both-sides contract as the collective-bytes gate: a run
  whose baseline carried no devicescope window (the 0→nonzero window
  transition) is noted, never indicted;
* **is noise-aware** — in trajectory mode (``--dir``) the baseline is
  the MEDIAN of all usable prior artifacts and the effective threshold
  is ``max(--threshold, --noise-mult × observed relative spread)``, so
  a comparison across a noisy history demands a drop larger than the
  history's own scatter before it indicts a PR.

Usage:
    python tools/perf_regress.py BASELINE.json CANDIDATE.json
    python tools/perf_regress.py --dir REPO_DIR [--candidate FILE]

Accepted artifact shapes: direct bench.py output
(``{"metric", "value", ...}``) and the driver wrapper
(``{"n", "cmd", "rc", "parsed": {...}}``).

Exit status: 0 = no regression (or nothing comparable — every baseline
was an env failure), 1 = regression, 2 = usage/IO error.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

__all__ = ["load_artifact", "compare", "trajectory", "main"]

DEFAULT_THRESHOLD = 0.05       # 5% relative drop on value / MFU
DEFAULT_P99_THRESHOLD = 0.25   # 25% relative increase on p99
# collective payload is DETERMINISTIC for a fixed model+layout (static
# HLO inventory, no timing noise), so the gate is tight: a real layout
# change moves it by integer factors, measurement scatter by zero
DEFAULT_COLL_THRESHOLD = 0.10  # 10% relative increase on bytes/step
# measured device busy fraction (devicescope window): a >10% relative
# drop means the chip spent measurably more of the window idle
DEFAULT_BUSY_THRESHOLD = 0.10
# measured peak memory (memscope watermark ring, static footprint
# fallback): >10% growth is a memory regression — the number that eats
# the autotuner's batch headroom and ends runs in RESOURCE_EXHAUSTED
DEFAULT_PEAK_THRESHOLD = 0.10
# dedup rate (extra.embedding.dedup_rate, recsys artifacts): for a
# fixed record stream the id distribution is deterministic, so like the
# collective inventory this has no timing scatter — and a drop is a
# silent comms blowup (the sharded gather's payload scales with
# 1 - dedup_rate)
DEFAULT_DEDUP_THRESHOLD = 0.10
DEFAULT_NOISE_MULT = 2.0


def load_artifact(path):
    """Load one BENCH artifact → (record | None, skip_reason | None).

    The record is {path, metric, value, unit, mfu, p99_ms}; None means
    the artifact is unusable as a perf number (the reason says why —
    env failure, error, unparseable)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable/invalid JSON ({e})"
    if not isinstance(doc, dict):
        return None, "not a JSON object"
    if "parsed" in doc and "metric" not in doc:
        # driver wrapper: the bench's own JSON line lives under `parsed`
        doc = doc["parsed"]
        if not isinstance(doc, dict):
            return None, "driver wrapper with no parsed bench line " \
                         "(the run produced no usable output)"
    if doc.get("status") == "env_failure":
        return None, f"env_failure: {str(doc.get('error', ''))[:80]}"
    if doc.get("error"):
        # pre-perfscope artifacts (BENCH_r02–r05) carry only `error`;
        # value 0 + error is an environment/run failure either way
        return None, f"errored run: {str(doc['error'])[:80]}"
    value = doc.get("value")
    if not isinstance(value, (int, float)) or isinstance(value, bool) \
            or value <= 0:
        return None, f"non-positive value {value!r}"
    extra = doc.get("extra") or {}
    serving = extra.get("serving") or {}
    commscope = extra.get("commscope") or {}
    step = commscope.get("step") if isinstance(commscope.get("step"),
                                               dict) else {}
    coll = step.get("bytes")
    rec = {
        "path": path,
        "metric": doc.get("metric"),
        "value": float(value),
        "unit": doc.get("unit"),
        "mfu": extra.get("mfu") if isinstance(extra.get("mfu"),
                                              (int, float)) else None,
        "p99_ms": serving.get("p99_ms") if isinstance(
            serving.get("p99_ms"), (int, float)) else None,
        # per-step in-program collective payload (commscope static-HLO
        # inventory of the steady train program) — the layout-regression
        # metric; None when the run carried no commscope step summary
        "coll_bytes": float(coll) if isinstance(coll, (int, float))
                      and not isinstance(coll, bool) else None,
        "resharding": step.get("resharding_collectives")
                      if isinstance(step.get("resharding_collectives"),
                                    int) else None,
    }
    # measured device busy fraction from a devicescope capture window —
    # None when the run carried no window (gate skipped: both-sides
    # contract, same as the commscope bytes gate)
    dsc = extra.get("devicescope") or {}
    bf = dsc.get("busy_fraction") if isinstance(dsc, dict) else None
    rec["busy_fraction"] = (float(bf)
                            if isinstance(bf, (int, float))
                            and not isinstance(bf, bool) else None)
    # measured peak memory from memscope's watermark ring (host RSS on
    # backends whose devices report no allocator stats), falling back
    # to the largest static per-program footprint; None when the run
    # didn't arm memscope (gate skipped: both-sides contract)
    msc = extra.get("memscope") or {}
    peak, src = None, None
    wm = msc.get("watermarks") if isinstance(msc, dict) else None
    for sect in ("device", "host_rss"):
        blk = (wm or {}).get(sect) if isinstance(wm, dict) else None
        pv = blk.get("peak") if isinstance(blk, dict) else None
        if isinstance(pv, (int, float)) and not isinstance(pv, bool) \
                and pv > 0:
            peak, src = float(pv), f"watermark {sect}"
            break
    if peak is None and isinstance(msc, dict):
        static = [p.get("peak_bytes") for p in (msc.get("programs") or [])
                  if isinstance(p, dict)
                  and isinstance(p.get("peak_bytes"), (int, float))
                  and not isinstance(p.get("peak_bytes"), bool)
                  and p["peak_bytes"] > 0]
        if static:
            peak, src = float(max(static)), "static footprint"
    rec["peak_bytes"] = peak
    rec["peak_source"] = src
    # serve_load sweep: the saturation knee (tools/serve_load.py). The
    # real gates are value (= QPS at the knee) and p99_ms (= p99 at the
    # knee, already in extra.serving); the knee's position itself is
    # reported as context — on a discrete ramp it can only move in
    # whole levels, so wobble is a note, never an indictment on its own
    sl = extra.get("serve_load") or {}
    kc = sl.get("knee_concurrency") if isinstance(sl, dict) else None
    rec["knee_concurrency"] = (int(kc) if isinstance(kc, int)
                               and not isinstance(kc, bool) else None)
    # embedding dedup rate (recsys artifacts) — None when the run
    # carried no extra.embedding (gate skipped: both-sides contract)
    emb = extra.get("embedding") or {}
    dr = emb.get("dedup_rate") if isinstance(emb, dict) else None
    rec["dedup_rate"] = (float(dr) if isinstance(dr, (int, float))
                         and not isinstance(dr, bool) else None)
    # the knob config the run ACTUALLY resolved to (extra.autotune.
    # resolved — present on every post-autotune training artifact,
    # tuned or not; `winner` is the fallback for tuned artifacts that
    # predate the resolved field). A tuner-chosen config change must
    # never be silently read as a code regression OR silently mask one,
    # so compare() attaches the knob diff as a context note — the same
    # both-sides contract as the commscope gates
    at = extra.get("autotune") or {}
    knobs = at.get("resolved") if isinstance(at.get("resolved"), dict) \
        else (at.get("winner") if isinstance(at.get("winner"), dict)
              else None)
    rec["knobs"] = knobs
    rec["autotune_cache_hit"] = (at.get("cache_hit")
                                 if isinstance(at.get("cache_hit"), bool)
                                 else None)
    # resilience accounting (extra.resilience): a RECOVERED run's BENCH
    # is USABLE — the measured throughput is real — but the recovery
    # cost (steps lost to rollbacks) must be reported, never hidden;
    # compare() notes it alongside the perf verdicts
    rx = extra.get("resilience") or {}
    rv = rx.get("recoveries_total") if isinstance(rx, dict) else None
    rec["recoveries"] = (int(rv) if isinstance(rv, (int, float))
                         and not isinstance(rv, bool) else None)
    sl_tot = rx.get("steps_lost_total") if isinstance(rx, dict) else None
    rec["steps_lost"] = (int(sl_tot)
                         if isinstance(sl_tot, (int, float))
                         and not isinstance(sl_tot, bool) else None)
    # fleetscope trace-join rate (extra.fleetscope): observability
    # coverage, NOT performance — a drop means spans stopped joining
    # (sampling change, a propagation break), so compare() reports it
    # as context under the both-sides contract, never as a perf verdict
    fsc = extra.get("fleetscope") or {}
    jr = fsc.get("join_rate") if isinstance(fsc, dict) else None
    rec["trace_join_rate"] = (float(jr)
                              if isinstance(jr, (int, float))
                              and not isinstance(jr, bool) else None)
    return rec, None


def _rel_spread(values):
    """Max relative deviation from the median — the trajectory's own
    noise band."""
    if len(values) < 2:
        return 0.0
    med = sorted(values)[len(values) // 2]
    if med <= 0:
        return 0.0
    return max(abs(v - med) / med for v in values)


def compare(baseline, candidate, threshold=DEFAULT_THRESHOLD,
            p99_threshold=DEFAULT_P99_THRESHOLD, noise=0.0,
            noise_mult=DEFAULT_NOISE_MULT,
            coll_threshold=DEFAULT_COLL_THRESHOLD,
            busy_threshold=DEFAULT_BUSY_THRESHOLD,
            peak_threshold=DEFAULT_PEAK_THRESHOLD,
            dedup_threshold=DEFAULT_DEDUP_THRESHOLD):
    """Compare two loaded records → (regressions, notes): lists of
    human-readable strings. Lower-is-worse metrics (value, mfu) regress
    on a relative DROP beyond the effective threshold; p99 and the
    per-step collective payload regress on a relative INCREASE — with
    collectives appearing where the baseline had NONE always flagged
    (0 → anything is the accidental-reshard signature, and a relative
    threshold on a zero baseline would wave it through)."""
    regressions, notes = [], []
    if baseline["metric"] != candidate["metric"]:
        notes.append(f"metric mismatch ({baseline['metric']!r} vs "
                     f"{candidate['metric']!r}) — nothing comparable")
        return regressions, notes
    # knob-config context FIRST, so every verdict below is read with it:
    # two artifacts measured under different tuner-resolved knob configs
    # are comparing configs as much as code — the diff is attached as a
    # note (never a verdict by itself), and its absence on either side
    # is noted too (both-sides contract, like the commscope gates)
    bk, ck = baseline.get("knobs"), candidate.get("knobs")
    if bk is not None and ck is not None:
        diff = sorted(k for k in set(bk) | set(ck)
                      if bk.get(k) != ck.get(k))
        if diff:
            detail = ", ".join(f"{k}: {bk.get(k)!r} -> {ck.get(k)!r}"
                               for k in diff)
            notes.append(
                f"CONTEXT: knob config differs baseline -> candidate "
                f"({detail}) — the verdicts below compare DIFFERENT "
                f"configs: a tuned-config change is not a code "
                f"regression, and can mask one (re-run both sides with "
                f"MXTPU_AUTOTUNE=0 and matching BENCH_* knobs to "
                f"isolate the code)")
        else:
            notes.append("ok knob config identical on both sides")
    elif (bk is None) != (ck is None):
        side = "candidate" if bk is None else "baseline"
        notes.append(f"note: only the {side} carries a resolved knob "
                     f"config — knob context skipped (needs "
                     f"extra.autotune on both sides)")
    eff = max(threshold, noise_mult * noise)
    if noise:
        notes.append(f"noise band {noise:.1%} -> effective threshold "
                     f"{eff:.1%}")
    for key, label in (("value", f"{candidate['unit'] or 'value'}"),
                       ("mfu", "MFU")):
        b, c = baseline.get(key), candidate.get(key)
        if b is None or c is None or b <= 0:
            continue
        drop = (b - c) / b
        line = (f"{label}: {b:.4g} -> {c:.4g} "
                f"({-drop:+.2%} vs threshold -{eff:.1%})")
        if drop > eff:
            regressions.append("REGRESSION " + line)
        else:
            notes.append("ok " + line)
    b99, c99 = baseline.get("p99_ms"), candidate.get("p99_ms")
    if b99 and c99 and b99 > 0:
        rise = (c99 - b99) / b99
        eff99 = max(p99_threshold, noise_mult * noise)
        line = (f"p99_ms: {b99:.4g} -> {c99:.4g} "
                f"({rise:+.2%} vs threshold +{eff99:.1%})")
        if rise > eff99:
            regressions.append("REGRESSION " + line)
        else:
            notes.append("ok " + line)
    bcb, ccb = baseline.get("coll_bytes"), candidate.get("coll_bytes")
    if bcb is not None and ccb is not None:
        if bcb <= 0:
            if ccb > 0:
                regressions.append(
                    f"REGRESSION collective bytes/step: 0 -> {ccb:.0f} "
                    f"(in-program collectives appeared where the "
                    f"baseline layout had none — accidental reshard?)")
            else:
                notes.append("ok collective bytes/step: 0 -> 0")
        else:
            rise = (ccb - bcb) / bcb
            line = (f"collective bytes/step: {bcb:.0f} -> {ccb:.0f} "
                    f"({rise:+.2%} vs threshold +{coll_threshold:.1%})")
            # no noise widening: the static inventory has no scatter
            if rise > coll_threshold:
                regressions.append("REGRESSION " + line)
            else:
                notes.append("ok " + line)
    bbf, cbf = baseline.get("busy_fraction"), candidate.get("busy_fraction")
    if bbf is not None and cbf is not None and bbf > 0:
        drop = (bbf - cbf) / bbf
        effbf = max(busy_threshold, noise_mult * noise)
        line = (f"busy fraction: {bbf:.4f} -> {cbf:.4f} "
                f"({-drop:+.2%} vs threshold -{effbf:.1%})")
        if drop > effbf:
            regressions.append(
                "REGRESSION " + line + " (the chip measurably idler — "
                "see mxdiag.py device for the gap taxonomy)")
        else:
            notes.append("ok " + line)
    elif (bbf is None) != (cbf is None):
        # 0→nonzero (or nonzero→0) window transition: only one side ran
        # a devicescope capture window — there is no measured pair to
        # gate on, and inventing one would indict the act of measuring
        side = "candidate" if bbf is None else "baseline"
        notes.append(f"note: only the {side} carries a devicescope "
                     f"busy fraction — busy gate skipped (needs a "
                     f"window on both sides)")
    bpk, cpk = baseline.get("peak_bytes"), candidate.get("peak_bytes")
    if bpk is not None and cpk is not None and bpk > 0:
        if baseline.get("peak_source") != candidate.get("peak_source"):
            # a watermark peak and a static footprint are different
            # instruments — comparing them would manufacture a verdict
            notes.append(
                f"note: peak memory sources differ "
                f"({baseline.get('peak_source')} vs "
                f"{candidate.get('peak_source')}) — peak gate skipped "
                f"(needs the same instrument on both sides)")
        else:
            rise = (cpk - bpk) / bpk
            line = (f"peak memory ({candidate.get('peak_source')}): "
                    f"{bpk:.4g} -> {cpk:.4g} B "
                    f"({rise:+.2%} vs threshold +{peak_threshold:.1%})")
            if rise > peak_threshold:
                regressions.append(
                    "REGRESSION " + line + " (the run got hungrier — "
                    "see mxdiag.py mem for the footprint table)")
            else:
                notes.append("ok " + line)
    elif (bpk is None) != (cpk is None):
        # 0→nonzero memscope transition: only one side armed memscope —
        # a note, never an indictment (both-sides contract, same as the
        # devicescope busy gate)
        side = "candidate" if bpk is None else "baseline"
        notes.append(f"note: only the {side} carries a memscope peak — "
                     f"peak-memory gate skipped (needs memscope armed "
                     f"on both sides)")
    bkc, ckc = baseline.get("knee_concurrency"), \
        candidate.get("knee_concurrency")
    if bkc is not None and ckc is not None:
        if ckc < bkc:
            notes.append(f"note: saturation knee moved down "
                         f"({bkc} -> {ckc} clients) — the server "
                         f"saturates earlier; the QPS/p99-at-knee gates "
                         f"above carry the verdict")
        elif ckc > bkc:
            notes.append(f"note: saturation knee moved up "
                         f"({bkc} -> {ckc} clients)")
        else:
            notes.append(f"ok saturation knee: {bkc} clients (unchanged)")
    elif (bkc is None) != (ckc is None):
        side = "candidate" if bkc is None else "baseline"
        notes.append(f"note: only the {side} carries a serve_load knee "
                     f"— knee context skipped (needs a sweep on both "
                     f"sides)")
    # fleetscope trace-join rate: observability COVERAGE context, never
    # a perf verdict — the QPS/p99 gates above own the perf claim, this
    # says whether the cross-process spans behind them still join
    bjr, cjr = baseline.get("trace_join_rate"), \
        candidate.get("trace_join_rate")
    if bjr is not None and cjr is not None:
        if cjr < bjr - 0.05:
            notes.append(f"note: fleetscope trace-join rate dropped "
                         f"({bjr:.1%} -> {cjr:.1%}) — spans stopped "
                         f"joining (sampling change or a propagation "
                         f"break); coverage context, not a perf verdict")
        else:
            notes.append(f"ok fleetscope trace-join rate: {cjr:.1%} "
                         f"(baseline {bjr:.1%})")
    elif (bjr is None) != (cjr is None):
        side = "candidate" if bjr is None else "baseline"
        notes.append(f"note: only the {side} carries a fleetscope "
                     f"join rate — trace-coverage context skipped "
                     f"(needs fleetscope armed on both sides)")
    bdr, cdr = baseline.get("dedup_rate"), candidate.get("dedup_rate")
    if bdr is not None and cdr is not None and bdr > 0:
        drop = (bdr - cdr) / bdr
        # no noise widening: for a fixed record stream the dedup rate is
        # deterministic — any drop is a code change, not run-to-run jitter
        line = (f"dedup rate: {bdr:.4f} -> {cdr:.4f} "
                f"({-drop:+.2%} vs threshold -{dedup_threshold:.1%})")
        if drop > dedup_threshold:
            regressions.append(
                "REGRESSION " + line + " (the lookup dedup stopped "
                "compressing the sharded gather — the per-step "
                "collective bytes blow up with it; see docs/embedding.md)")
        else:
            notes.append("ok " + line)
    elif (bdr is None) != (cdr is None):
        side = "candidate" if bdr is None else "baseline"
        notes.append(f"note: only the {side} carries an embedding dedup "
                     f"rate — dedup gate skipped (needs extra.embedding "
                     f"on both sides)")
    cr = candidate.get("resharding")
    if cr:
        br = baseline.get("resharding")
        if br is None:
            # same contract as the bytes gate: a baseline that carried
            # no commscope data cannot indict a pre-existing count
            notes.append(f"note: candidate carries {cr} resharding "
                         f"collective(s); baseline has no commscope "
                         f"data — nothing to gate")
        elif cr > br:
            regressions.append(
                f"REGRESSION resharding collectives: {br} -> {cr} "
                f"(an annotation/axis-rule no longer matches the "
                f"computation — see mxdiag.py comms)")
        else:
            notes.append(f"note: candidate carries {cr} resharding "
                         f"collective(s) (not new vs baseline)")
    for side, rec in (("candidate", candidate), ("baseline", baseline)):
        recov = rec.get("recoveries")
        if recov:
            lost = rec.get("steps_lost")
            notes.append(
                f"note: {side} RECOVERED {recov} time(s)"
                + (f", {lost} step(s) lost to rollbacks" if lost else "")
                + " — run usable (throughput is real), recovery cost "
                  "tracked here so it is never hidden")
    return regressions, notes


def _natural_key(path):
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", os.path.basename(path))]


def trajectory(paths, threshold, p99_threshold, noise_mult,
               candidate_path=None,
               coll_threshold=DEFAULT_COLL_THRESHOLD,
               busy_threshold=DEFAULT_BUSY_THRESHOLD,
               peak_threshold=DEFAULT_PEAK_THRESHOLD,
               dedup_threshold=DEFAULT_DEDUP_THRESHOLD):
    """Directory mode: newest usable artifact vs the median of all
    earlier usable ones, thresholds widened by the observed spread.
    Returns (exit_code, lines)."""
    lines = []
    loaded = []
    for p in sorted(paths, key=_natural_key):
        rec, why = load_artifact(p)
        if rec is None:
            lines.append(f"skip {p}: {why}")
        else:
            loaded.append(rec)
    if candidate_path:
        cand, why = load_artifact(candidate_path)
        if cand is None:
            lines.append(f"candidate {candidate_path} unusable ({why}) — "
                         f"no perf verdict possible")
            return 0, lines
        base_pool = [r for r in loaded if r["path"] != candidate_path]
    else:
        if not loaded:
            lines.append("no usable artifacts at all — nothing to gate")
            return 0, lines
        cand = loaded[-1]
        base_pool = loaded[:-1]
    base_pool = [r for r in base_pool if r["metric"] == cand["metric"]]
    if not base_pool:
        lines.append(f"no usable baseline for metric {cand['metric']!r} "
                     f"(every prior artifact skipped) — nothing to gate")
        return 0, lines
    values = [r["value"] for r in base_pool]
    values_sorted = sorted(values)
    med_val = values_sorted[len(values_sorted) // 2]
    base = dict(min(base_pool, key=lambda r: abs(r["value"] - med_val)))
    base["path"] = f"median of {len(base_pool)} artifacts"
    noise = _rel_spread(values)
    lines.append(f"candidate: {cand['path']} ({cand['value']:.4g} "
                 f"{cand['unit']})")
    lines.append(f"baseline: {base['path']} "
                 f"(median value {base['value']:.4g})")
    regs, notes = compare(base, cand, threshold=threshold,
                          p99_threshold=p99_threshold, noise=noise,
                          noise_mult=noise_mult,
                          coll_threshold=coll_threshold,
                          busy_threshold=busy_threshold,
                          peak_threshold=peak_threshold,
                          dedup_threshold=dedup_threshold)
    lines.extend(notes + regs)
    return (1 if regs else 0), lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH regression gate (env-failure-aware, "
                    "noise-aware)")
    ap.add_argument("files", nargs="*",
                    help="BASELINE.json CANDIDATE.json (pairwise mode)")
    ap.add_argument("--dir", default=None,
                    help="trajectory mode: gate the newest usable "
                         "BENCH_*.json in DIR against the median of the "
                         "earlier ones")
    ap.add_argument("--candidate", default=None,
                    help="with --dir: explicit candidate artifact")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative drop threshold for value/MFU "
                         "(default 0.05)")
    ap.add_argument("--p99-threshold", type=float,
                    default=DEFAULT_P99_THRESHOLD,
                    help="relative increase threshold for p99 "
                         "(default 0.25)")
    ap.add_argument("--noise-mult", type=float, default=DEFAULT_NOISE_MULT,
                    help="noise-band multiplier in trajectory mode "
                         "(default 2.0)")
    ap.add_argument("--coll-threshold", type=float,
                    default=DEFAULT_COLL_THRESHOLD,
                    help="relative increase threshold for per-step "
                         "collective bytes (default 0.10; a zero "
                         "baseline flags ANY appearance)")
    ap.add_argument("--busy-threshold", type=float,
                    default=DEFAULT_BUSY_THRESHOLD,
                    help="relative drop threshold for the measured "
                         "device busy fraction (default 0.10; skipped "
                         "unless BOTH sides carry a devicescope window)")
    ap.add_argument("--peak-threshold", type=float,
                    default=DEFAULT_PEAK_THRESHOLD,
                    help="relative increase threshold for measured peak "
                         "memory bytes (default 0.10; skipped unless "
                         "BOTH sides carry memscope data from the same "
                         "instrument)")
    ap.add_argument("--dedup-threshold", type=float,
                    default=DEFAULT_DEDUP_THRESHOLD,
                    help="relative drop threshold for the embedding "
                         "lookup dedup rate (default 0.10; skipped "
                         "unless BOTH sides carry extra.embedding)")
    args = ap.parse_args(argv)

    if args.dir:
        paths = glob.glob(os.path.join(args.dir, "BENCH_*.json"))
        if not paths:
            print(f"perf_regress: no BENCH_*.json under {args.dir}",
                  file=sys.stderr)
            return 2
        rc, lines = trajectory(paths, args.threshold, args.p99_threshold,
                               args.noise_mult,
                               candidate_path=args.candidate,
                               coll_threshold=args.coll_threshold,
                               busy_threshold=args.busy_threshold,
                               peak_threshold=args.peak_threshold,
                               dedup_threshold=args.dedup_threshold)
        for ln in lines:
            print(ln)
        print("perf_regress: " + ("REGRESSION" if rc else "OK"))
        return rc

    if len(args.files) != 2:
        ap.print_usage(sys.stderr)
        print("perf_regress: pairwise mode takes exactly BASELINE and "
              "CANDIDATE", file=sys.stderr)
        return 2
    base, why_b = load_artifact(args.files[0])
    cand, why_c = load_artifact(args.files[1])
    if base is None:
        print(f"skip baseline {args.files[0]}: {why_b} — nothing to gate")
        return 0
    if cand is None:
        print(f"skip candidate {args.files[1]}: {why_c} — no perf verdict "
              f"possible")
        return 0
    regs, notes = compare(base, cand, threshold=args.threshold,
                          p99_threshold=args.p99_threshold,
                          coll_threshold=args.coll_threshold,
                          busy_threshold=args.busy_threshold,
                          peak_threshold=args.peak_threshold,
                          dedup_threshold=args.dedup_threshold)
    for ln in notes + regs:
        print(ln)
    print("perf_regress: " + ("REGRESSION" if regs else "OK"))
    return 1 if regs else 0


if __name__ == "__main__":
    sys.exit(main())
