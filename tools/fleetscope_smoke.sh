#!/bin/bash
# Tier-1 fleetscope smoke (CPU-only, no TPU, no tunnel): proves the
# cross-process tracing claims end to end on a spawned 2-replica CPU
# lenet fleet driven by serve_load (every request carries a
# client-minted W3C traceparent, sample=1 so every request is a span):
#   (a) traces JOIN — >= 95% of router-observed successful forwards
#       have a replica-side span with the matching trace_id and a
#       parent_id equal to the router's span (one request = ONE trace);
#   (b) the accounting ADDS UP — per joined trace, router overhead
#       (e2e - forward) + wire gap (forward - replica e2e) + the
#       replica span's five-way attribution reconstruct the router's
#       e2e within 15% at the median (the wire gap is a difference of
#       perf_counter durations, so clock skew cannot enter it);
#   (c) the collector PULLED — every replica's diagnostics.export
#       endpooint answered at least once, with a finite offset bound;
#   (d) the views RENDER and the artifacts VALIDATE — mxdiag trace/pod
#       exit 0 on the real artifacts, trace_check accepts the BENCH
#       json, the harness + per-replica event logs, and the merged
#       mxtpu.events/2 timeline.
set -u
cd "$(dirname "$0")/.." || exit 1

SMOKE_DIR=${MXTPU_FLEETSCOPE_SMOKE_DIR:-/tmp/mxtpu_fleetscope_smoke}
rm -rf "$SMOKE_DIR"; mkdir -p "$SMOKE_DIR"
export JAX_PLATFORMS=cpu

OUT="$SMOKE_DIR/fleet2.json"
EVENTS="$SMOKE_DIR/events.jsonl"

echo "fleetscope_smoke: 2-replica spawned fleet under serve_load"
echo "fleetscope_smoke: (sample=1: every request minted AND spanned)"
timeout -k 10 900 python tools/serve_load.py --fleet 2 \
  --ramp 4,8 --level-requests 64 --sample 1 \
  --fleet-cache "$SMOKE_DIR/aot_cache" \
  --out "$OUT" --events "$EVENTS" > "$SMOKE_DIR/serve_load.log" 2>&1
rc=$?
if [ "$rc" != "0" ]; then
  echo "fleetscope_smoke: serve_load failed rc=$rc"
  tail -30 "$SMOKE_DIR/serve_load.log"; exit 1
fi

# every artifact must validate structurally: the BENCH json, the
# harness (router) events log, and each worker's own events log
python tools/trace_check.py "$OUT" "$EVENTS" \
  "$SMOKE_DIR"/events_replica_*.jsonl || exit 1

# (a)+(b)+(c): join rate, accounting identity, collector pulls
python - "$OUT" "$EVENTS" "$SMOKE_DIR" <<'EOF' || exit 1
import glob, json, os, sys

doc = json.load(open(sys.argv[1]))
events_path, smoke_dir = sys.argv[2], sys.argv[3]
fs = (doc.get("extra") or {}).get("fleetscope") or {}
assert fs, "serve_load wrote no extra.fleetscope"

# (a) >= 95% of sampled forwards joined
assert fs["sampled"] > 0, fs
rate = fs["join_rate"]
assert rate >= 0.95, \
    f"only {rate:.1%} of {fs['sampled']} traces joined " \
    f"({fs['unjoined_forwards']} unjoined)"
assert fs["client_minted"] >= fs["sampled"], fs
gap = fs.get("wire_gap_ms") or {}
assert gap.get("p50") is not None and gap["p50"] >= -1.0, gap
rows = fs.get("per_replica") or []
assert len(rows) == 2 and all(r["traces"] > 0 for r in rows), \
    f"a replica joined no traces: {rows}"

# (c) the collector pulled every replica at least once
coll = fs.get("collector") or {}
procs = coll.get("processes") or {}
assert len(procs) == 2, f"collector saw {len(procs)} processes"
for name, p in procs.items():
    assert p["pulls"] > 0, f"{name}: no successful pull ({p})"
    assert p["offset_bound_s"] is not None and \
        p["offset_bound_s"] >= 0, p

# (b) re-derive the accounting from the RAW event logs: router
# overhead + wire gap + the replica span's five components must
# reconstruct the router's e2e (the components sum to replica e2e by
# the servescope identity; the wire gap closes the rest)
def recs(path, name):
    out = []
    for ln in open(path):
        r = json.loads(ln)
        if r.get("name") == name:
            out.append(r)
    return out

rtr = {r["args"]["trace_id"]: r["args"]
       for r in recs(events_path, "fleetscope.request")
       if r["args"].get("status") == 200}
rep = {}
for p in glob.glob(os.path.join(smoke_dir, "events_replica_*.jsonl")):
    for r in recs(p, "serving.request"):
        tid = (r.get("args") or {}).get("trace_id")
        if tid:
            rep[tid] = r["args"]
COMPONENTS = ("queue_wait_ms", "coalesce_delay_ms", "pad_overhead_ms",
              "device_exec_ms", "respond_ms")
errs = []
for tid, ra in rtr.items():
    pa = rep.get(tid)
    if pa is None or "forward_ms" not in ra or "e2e_ms" not in pa:
        continue
    overhead = ra["e2e_ms"] - ra["forward_ms"]
    wire = ra["forward_ms"] - pa["e2e_ms"]
    comp = sum(pa.get(k, 0.0) for k in COMPONENTS)
    rebuilt = overhead + wire + comp
    errs.append(abs(rebuilt - ra["e2e_ms"]) / max(ra["e2e_ms"], 1e-9))
assert len(errs) >= 0.95 * len(rtr), \
    f"only {len(errs)}/{len(rtr)} traces fully reconstructible"
errs.sort()
med = errs[len(errs) // 2]
assert med <= 0.15, \
    f"median accounting error {med:.1%} > 15%: the spans do not add up"

# hand one joined trace id to the renderer step
tid = next(t for t in rtr if t in rep)
open(os.path.join(smoke_dir, "trace_id.txt"), "w").write(tid)
print(f"fleetscope_smoke: {fs['joined']}/{fs['sampled']} joined "
      f"({rate:.1%}), wire gap p50 {gap['p50']:.2f} ms, median "
      f"accounting error {med:.2%} over {len(errs)} traces, "
      f"{sum(p['pulls'] for p in procs.values())} collector pulls")
EOF

# (d) the views must tell the story from the artifacts alone
TID=$(cat "$SMOKE_DIR/trace_id.txt")
python tools/mxdiag.py trace "$TID" "$EVENTS" \
  "$SMOKE_DIR"/events_replica_*.jsonl > "$SMOKE_DIR/mxdiag_trace.txt" \
  || { echo "fleetscope_smoke: mxdiag trace failed"; exit 1; }
grep -q "wire gap" "$SMOKE_DIR/mxdiag_trace.txt" || {
  echo "fleetscope_smoke: mxdiag trace lost the wire gap"; exit 1; }
python tools/mxdiag.py pod "$OUT" > "$SMOKE_DIR/mxdiag_pod.txt" \
  || { echo "fleetscope_smoke: mxdiag pod failed"; exit 1; }
grep -q "replica0" "$SMOKE_DIR/mxdiag_pod.txt" || {
  echo "fleetscope_smoke: mxdiag pod lost the replica table"; exit 1; }

# the clock-aligned merge must produce a valid mxtpu.events/2 stream
python tools/mxdiag.py merge "$EVENTS" \
  "$SMOKE_DIR"/events_replica_*.jsonl -o "$SMOKE_DIR/merged.jsonl" \
  --tail 5 > /dev/null || exit 1
python tools/trace_check.py "$SMOKE_DIR/merged.jsonl" || exit 1
grep -q '"schema": "mxtpu.events/2"' "$SMOKE_DIR/merged.jsonl" || {
  echo "fleetscope_smoke: merge did not write mxtpu.events/2"; exit 1; }

# the join-rate context note must ride the perf_regress report
python tools/perf_regress.py "$OUT" "$OUT" \
  > "$SMOKE_DIR/perf_regress.txt" || {
  echo "fleetscope_smoke: perf_regress rejected the artifact"; exit 1; }
grep -q "fleetscope trace-join rate" "$SMOKE_DIR/perf_regress.txt" || {
  echo "fleetscope_smoke: perf_regress lost the join-rate context"
  exit 1; }

echo "fleetscope_smoke: all fleetscope artifacts validate"
