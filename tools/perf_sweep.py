#!/usr/bin/env python
"""TPU perf sweep orchestrator (round-3 protocol).

Runs bench.py as a SUBPROCESS per configuration — the exact code path the
driver runs — so every compile lands in the same persistent cache
(.jax_cache) the driver's run will hit. Writes PERF.md with the sweep
table and prints the best config.

Safety protocol (the round-2 wedge must not repeat):
  * probe the tunnel with a tiny matmul + HOST FETCH (60 s timeout) before
    anything big; abort immediately if it fails;
  * step batch sizes up gradually; batch 256 ONLY with remat
    (256-no-remat is banned — it wedged the shared tunnel for 8+ hours);
  * one bench process at a time; each gets its own timeout; a timeout
    aborts the remaining sweep (the tunnel is presumed unhealthy).

Usage:  python tools/perf_sweep.py [--quick]
"""
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"sweep[{time.strftime('%H:%M:%S')}]: {msg}", flush=True)


def probe(timeout=60):
    """Tiny matmul + host fetch through a fresh process. True = healthy."""
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((128, 128), jnp.bfloat16);"
            "print(float((x @ x).sum()))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True, cwd=ROOT)
        ok = r.returncode == 0 and r.stdout.strip()
        log(f"probe: rc={r.returncode} out={r.stdout.strip()[:40]!r}")
        return bool(ok)
    except subprocess.TimeoutExpired:
        log("probe TIMED OUT — tunnel wedged, aborting")
        return False


def _last_json_line(stdout):
    for ln in reversed(stdout.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
    return None


def run_bench(env_overrides, timeout):
    # driver-parity: ALWAYS drop BENCH_* exported in the caller's shell —
    # a stray BENCH_MODEL/BENCH_DTYPE would silently mislabel every row
    # (and the no-override warm run must be the driver's exact config)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}
    env.update({k: str(v) for k, v in env_overrides.items()})
    desc = " ".join(f"{k}={v}" for k, v in env_overrides.items()) or "default"
    log(f"bench: {desc}")
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "bench.py"], timeout=timeout,
                           capture_output=True, text=True, cwd=ROOT, env=env)
    except subprocess.TimeoutExpired:
        log(f"bench TIMED OUT after {timeout}s: {desc}")
        return None
    wall = time.time() - t0
    out = _last_json_line(r.stdout)
    if out is None:
        log(f"bench produced no JSON (rc={r.returncode}); stderr tail: "
            f"{r.stderr[-300:]}")
        return None
    out["_wall_s"] = round(wall, 1)
    out["_config"] = desc
    if out.get("error"):
        log(f"bench error: {out['error'][:200]}")
        return None
    log(f"  -> {out['value']} {out['unit']} "
        f"(mfu={out.get('extra', {}).get('mfu')}, wall={wall:.0f}s)")
    return out


PALLAS_TAG = os.environ.get("PALLAS_TAG", "r04")


def run_pallas_validation(timeout=1800):
    """Stage 0: compiled pallas kernels vs XLA on the chip (VERDICT r3
    weak #3) — parity must hold BEFORE the protected bench risks the
    tunnel on a Mosaic bug. Writes docs/pallas_onchip_<PALLAS_TAG>.md."""
    log("stage 0: pallas on-chip validation")
    try:
        r = subprocess.run([sys.executable, "tools/pallas_onchip.py"],
                           timeout=timeout, capture_output=True, text=True,
                           cwd=ROOT)
    except subprocess.TimeoutExpired:
        log("pallas validation TIMED OUT — treating tunnel as unhealthy")
        return "timeout"
    log(f"pallas validation rc={r.returncode}")
    out = _last_json_line(r.stdout)
    if out is None:
        log(f"no JSON from pallas validation (crash); stderr: "
            f"{r.stderr[-300:]}")
    return out


def main():
    quick = "--quick" in sys.argv
    if not probe():
        sys.exit(2)

    pallas_res = None
    if "--skip-pallas" not in sys.argv:
        pallas_res = run_pallas_validation()
        if pallas_res == "timeout":
            # a timeout IS the wedge signature (round-2 postmortem); the
            # tiny probe is not sufficient clearance after one
            log("aborting: pallas validation timed out (tunnel presumed "
                "wedged)")
            sys.exit(2)
        if pallas_res is None:
            # clean crash (Mosaic lowering bug etc.) — exactly what stage
            # 0 exists to surface; re-probe and continue on the XLA path
            # rather than killing the long-awaited bench run
            if not probe():
                log("aborting: tunnel unhealthy after pallas validation")
                sys.exit(2)
            log("pallas validation crashed but tunnel is healthy — "
                "continuing sweep on the XLA path; fix the kernels")
        elif not pallas_res.get("is_tpu"):
            # jax silently fell back to CPU: the TPU is unreachable for
            # this environment, and every bench subprocess would fall back
            # the same way — PERF.md would publish CPU numbers as TPU
            log("aborting: pallas validation ran on CPU (is_tpu=false) — "
                "the TPU backend is not reachable; refusing to publish "
                "CPU throughput as a TPU sweep")
            sys.exit(2)
        elif not pallas_res.get("all_ok"):
            log("pallas kernels FAILED parity on chip — sweep continues "
                "(bench uses the XLA path), but fix before enabling pallas")

    results = []

    def record(cfg, timeout=3600):
        res = run_bench(cfg, timeout)
        if res is not None:
            results.append(res)
        return res

    def cache_size():
        d = os.path.join(ROOT, ".jax_cache")
        total, biggest = 0, 0
        try:
            for fn in os.listdir(d):
                sz = os.path.getsize(os.path.join(d, fn))
                total += sz
                biggest = max(biggest, sz)
        except OSError:
            pass
        return total, biggest

    # 0.5) CACHE WARM — the round-2 TPU-compiled ResNet step fell out of
    # .jax_cache (VERDICT r4 weak #2), so the driver's protected bench
    # would pay the full remote compile inside its watchdog. Run bench.py
    # with NO overrides — the driver's EXACT config (BENCH_K defaults to
    # 8, batch 128) — so both its single-step and k-scan programs land in
    # the cache, then verify a big entry exists before sweeping.
    t_before, b_before = cache_size()
    log(f"stage 0.5: cache warm (driver-default config); .jax_cache "
        f"total={t_before >> 20} MB biggest={b_before >> 20} MB")
    # always run even if a big entry already exists: the warm run doubles
    # as the driver-default (K=8) data row, and on a warm cache it's a
    # cheap cache hit, not a fresh compile
    warm = record({}, timeout=3600)
    t_after, b_after = cache_size()
    log(f"cache after warm: total={t_after >> 20} MB "
        f"biggest={b_after >> 20} MB "
        f"({'OK: large TPU entry present' if b_after > 10 << 20 else 'WARN: no >10 MB entry — driver bench may still pay the compile'})")
    if warm is None:
        log("aborting: driver-default warm run failed/timed out")
        sys.exit(2)

    steps = 20
    # pin K: bench.py defaults resnet50 to BENCH_K=8, but the sweep
    # isolates K explicitly per config
    # K1_CONTROL off inside the sweep: BENCH_K=1 is its own isolated row
    # here, so the in-bench control would be redundant tunnel risk (the
    # scrubbed warm run above keeps it — driver parity)
    base = {"BENCH_STEPS": steps, "BENCH_K": 1, "BENCH_K1_CONTROL": 0}
    aborted = False
    # 1) dispatch-vs-compute: K sweep at the round-2 config (b128, already
    #    the cheapest compile; K=1 first so the base step compiles alone;
    #    K=8 is covered by the driver-default warm run above)
    for k in ([1] if quick else [1, 5, 20]):
        if record({**base, "BENCH_K": k}) is None:
            log("aborting sweep (unhealthy run)")
            aborted = True
            break
    else:
        # 2) stem + batch sweep, gradual; 256 ONLY with remat (hard rule).
        #    Both K8 variants (with and without S2D) are kept at each
        #    batch size so S2D's effect is isolated, not confounded with K.
        for cfg in ([] if quick else
                    [{"BENCH_S2D": 1},
                     {"BENCH_S2D": 1, "BENCH_K": 8},
                     {"BENCH_BATCH": 192},
                     {"BENCH_BATCH": 192, "BENCH_K": 8},
                     {"BENCH_BATCH": 192, "BENCH_K": 8, "BENCH_S2D": 1},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1, "BENCH_K": 8},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1, "BENCH_K": 8,
                      "BENCH_S2D": 1}]):
            assert not (cfg.get("BENCH_BATCH", 0) >= 256
                        and not cfg.get("BENCH_REMAT")), "banned config"
            if record({**base, **cfg}) is None:
                log("aborting batch sweep (unhealthy run)")
                aborted = True
                break

    # 2.5) whole-loop executor (mxtpu.trainloop, PR 6): k-chunked dispatch
    #      + device-side prefetch + per-micro-step lr. Same scan program
    #      family as BENCH_K (cache-friendly), plus the io.*/trainloop.*
    #      telemetry lands in the BENCH json; pallas selection rides the
    #      on-TPU defaults.
    if not aborted:
        for cfg in ([{"BENCH_LOOP_CHUNK": 8}] if quick else
                    [{"BENCH_LOOP_CHUNK": 8},
                     {"BENCH_LOOP_CHUNK": 8, "BENCH_S2D": 1}]):
            if record({**base, **cfg}) is None:
                log("aborting trainloop stage (unhealthy run)")
                aborted = True
                break

    # 3) model stage: BERT (BASELINE config 2; first-ever chip number —
    #    VERDICT r3 next-step #4) then transformer_lm (the causal-LM
    #    family's first chip number). Flash attention pays in both;
    #    default batches from bench.py, one K variant each. HARD RULE:
    #    any earlier timeout means the tunnel is presumed unhealthy — a
    #    fresh large-model compile on a sick tunnel is exactly the
    #    round-2 wedge; the tiny probe is not sufficient clearance after
    #    an abort.
    if results and not aborted and probe():
        for cfg in ([{"BENCH_MODEL": "bert"}] if quick else
                    [{"BENCH_MODEL": "bert"},
                     {"BENCH_MODEL": "bert", "BENCH_K": 8},
                     {"BENCH_MODEL": "transformer_lm"},
                     {"BENCH_MODEL": "transformer_lm", "BENCH_K": 8}]):
            if record({**base, **cfg}) is None:
                log("aborting model stage (unhealthy run)")
                break

    if not results:
        log("no successful runs")
        sys.exit(1)

    resnet = [r for r in results if "BENCH_MODEL" not in r["_config"]]
    bert = [r for r in results if "bert" in r["_config"]]
    best = max(resnet, key=lambda r: r["value"]) if resnet else results[0]
    lines = [
        "# PERF — TPU sweep (one v5e chip via axon tunnel)",
        "",
        f"Sweep of {time.strftime('%Y-%m-%d %H:%M')} — fused train steps,",
        "bf16, numbers from `bench.py` subprocess runs (the driver's exact",
        "path; compiles cached in `.jax_cache`). `k` = micro-steps",
        "dispatched as ONE XLA program (`FusedTrainStep.run_k`); wall",
        "includes per-run process startup.",
        "",
        "| config | value | unit | MFU | wall (s) |",
        "|---|---|---|---|---|",
    ]
    for r in results:
        e = r.get("extra", {})
        lines.append(f"| {r['_config']} | {r['value']} | {r['unit']} | "
                     f"{e.get('mfu', '?')} | {r['_wall_s']} |")
    lines += [
        "",
        f"**Best ResNet-50: {best['_config']} → {best['value']} img/s "
        f"(MFU {best.get('extra', {}).get('mfu')})**",
    ]
    if bert:
        bb = max(bert, key=lambda r: r["value"])
        lines.append(f"**BERT: {bb['_config']} → {bb['value']} "
                     f"{bb['unit']} (MFU "
                     f"{bb.get('extra', {}).get('mfu')})**")
    lm = [r for r in results if "transformer_lm" in r["_config"]]
    if lm:
        lb = max(lm, key=lambda r: r["value"])
        lines.append(f"**TransformerLM: {lb['_config']} → {lb['value']} "
                     f"{lb['unit']} (MFU "
                     f"{lb.get('extra', {}).get('mfu')})**")
    if pallas_res is not None:
        lines += ["",
                  "Pallas on-chip validation: "
                  + ("ALL OK" if pallas_res.get("all_ok") else "FAILURES")
                  + f" — see docs/pallas_onchip_{PALLAS_TAG}.md for the "
                  "parity and kernel-vs-XLA timing table."]
    lines += [
        "",
        "Protocol notes: tunnel probed with a 60 s matmul+fetch before the",
        "sweep; batch 256 runs only with remat (a 256-no-remat compile",
        "wedged the shared tunnel in round 2 and is banned); host value",
        "fetch is the only true barrier through the relay, so every timed",
        "segment ends in one.",
    ]
    with open(os.path.join(ROOT, "PERF.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    log(f"PERF.md written; best = {best['_config']} @ {best['value']}")
    print(json.dumps({"best": best}, indent=2))


if __name__ == "__main__":
    main()
