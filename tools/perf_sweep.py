#!/usr/bin/env python
"""TPU perf sweep orchestrator (round-3 protocol), rebased onto the
mxtpu.autotune TRIAL RUNNER: every row executes through
``autotune.trial.run_trial`` — the exact subprocess protocol the tuner's
search uses (same env scrubbing, same devicescope measurement arming,
same artifact parsing) — so the manual sweep and the autotuner can
NEVER disagree on how a config is measured, and the sweep's rows are
valid trial records the tuning cache ingests at the end
(``TuningCache.ingest``): a driver run with ``MXTPU_AUTOTUNE=1`` then
starts from the sweep's best config with zero trials.

Runs bench.py as a SUBPROCESS per configuration — the exact code path the
driver runs — so every compile lands in the same persistent cache
(.jax_cache) the driver's run will hit. Writes PERF.md with the sweep
table and prints the best config.

Safety protocol (the round-2 wedge must not repeat):
  * probe the tunnel with a tiny matmul + HOST FETCH (60 s timeout) before
    anything big; abort immediately if it fails;
  * step batch sizes up gradually; batch 256 ONLY with remat
    (256-no-remat is banned — it wedged the shared tunnel for 8+ hours);
  * one bench process at a time; each gets its own timeout; a timeout
    aborts the remaining sweep (the tunnel is presumed unhealthy).

Usage:  python tools/perf_sweep.py [--quick]
"""
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from incubator_mxnet_tpu.autotune import cache as at_cache  # noqa: E402
from incubator_mxnet_tpu.autotune import trial as at_trial  # noqa: E402
from incubator_mxnet_tpu.autotune.knobs import KnobConfig  # noqa: E402


def log(msg):
    print(f"sweep[{time.strftime('%H:%M:%S')}]: {msg}", flush=True)


def probe(timeout=60):
    """Tiny matmul + host fetch through a fresh process. True = healthy."""
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((128, 128), jnp.bfloat16);"
            "print(float((x @ x).sum()))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True, cwd=ROOT)
        ok = r.returncode == 0 and r.stdout.strip()
        log(f"probe: rc={r.returncode} out={r.stdout.strip()[:40]!r}")
        return bool(ok)
    except subprocess.TimeoutExpired:
        log("probe TIMED OUT — tunnel wedged, aborting")
        return False


# sweep-row BENCH_* spellings that ARE knob fields: these pin the trial
# through KnobConfig (the canonical spelling run_trial exports), the
# rest (BENCH_K, BENCH_S2D, BENCH_MODEL, ...) ride as raw extras
_KNOB_ENV = {"BENCH_LOOP_CHUNK": ("loop_chunk", int),
             "BENCH_REMAT": ("remat", lambda v: str(v) == "1"),
             "BENCH_REMAT_POLICY": ("remat_policy", str),
             "BENCH_PREFETCH_DEPTH": ("prefetch_depth", int),
             "BENCH_MESH": ("mesh", str),
             "BENCH_BATCH": ("batch", int)}


def _split_knobs(env_overrides):
    """One sweep row -> (KnobConfig | None, raw extras). None when the
    row sets no knob fields (the driver-parity warm run must export NO
    knob env at all — bench resolves its own defaults)."""
    knobs, extras = {}, {}
    for k, v in env_overrides.items():
        if k in _KNOB_ENV:
            field, conv = _KNOB_ENV[k]
            knobs[field] = conv(v)
        else:
            extras[k] = v
    return (KnobConfig(**knobs) if knobs else None), extras


def run_bench(env_overrides, timeout, measure=True):
    """One sweep row through the autotune trial runner (the ONE way a
    config is measured — docs/autotune.md). run_trial scrubs the
    caller's BENCH_* (driver parity: a stray BENCH_MODEL would silently
    mislabel every row), pins the row's knobs via their canonical
    spellings, and — with measure=True — arms the devicescope window so
    every row carries measured busy provenance, exactly like a tuner
    trial. measure=False is the driver-parity warm run (no overrides,
    no measurement arming — the driver's EXACT config).

    Returns the TrialResult (status "failed" => unhealthy run, treated
    like the old None: abort the stage)."""
    cfg, extras = _split_knobs({k: str(v)
                                for k, v in env_overrides.items()})
    desc = " ".join(f"{k}={v}" for k, v in env_overrides.items()) \
        or "default"
    log(f"bench: {desc}" + ("" if measure else " (driver parity)"))
    # driver parity (measure=False) keeps ambient MXTPU_* knobs: an
    # operator-exported MXTPU_LOOP_CHUNK is part of what the driver
    # actually runs; search-style rows scrub — their config pins all
    r = at_trial.run_trial(cfg, timeout=timeout, measure=measure,
                           extra_env=extras, steps=None,
                           scrub_ambient=measure,
                           bench_path=os.path.join(ROOT, "bench.py"))
    r.desc = desc
    r.extras = extras       # the non-knob row spellings (BENCH_K, ...)
    if not r.ok:
        log(f"bench FAILED ({desc}): {r.error}")
        return None
    m = r.measurement
    r.artifact["_wall_s"] = r.wall_s
    r.artifact["_config"] = desc
    log(f"  -> {r.artifact['value']} {r.artifact['unit']} "
        f"(mfu={m.get('mfu')}, busy={m.get('busy_fraction')}, "
        f"wall={r.wall_s:.0f}s)")
    return r


def _ingest_into_cache(trial_records):
    """Group the sweep's OK knob-pinned rows by tuning-cache key and
    store each group's best as that key's winner (TuningCache.ingest).
    Device kind comes from each artifact's perfscope peaks table — the
    orchestrator itself never touches the backend (wedge protocol)."""
    groups = {}
    for tr in trial_records:
        if not tr.ok or tr.config is None:
            continue
        extras = getattr(tr, "extras", {}) or {}
        model = extras.get("BENCH_MODEL", "resnet50")
        dtype = extras.get("BENCH_DTYPE", "bfloat16")
        # bench.py's table is the one home for per-model default batch
        # (a row without BENCH_BATCH ran at that batch, and the cache
        # key must record the real number the driver will key on)
        import bench as bench_mod
        batch = tr.config.batch or bench_mod.DEFAULT_BATCH.get(model)
        peaks = ((tr.artifact.get("extra") or {}).get("perfscope")
                 or {}).get("peaks") or {}
        dk = peaks.get("device_kind") or "unknown"
        key = (at_cache.fingerprint(tag=model, batch=batch, dtype=dtype),
               tr.config.mesh, dk)
        groups.setdefault(key, []).append(tr)
    if not groups:
        log("cache ingest: no knob-pinned rows to ingest")
        return
    cache = at_cache.TuningCache()
    # str key: mesh is None for unsharded rows and a token for sharded
    # ones — a plain tuple sort would TypeError comparing None to str
    for (fp, mesh, dk), records in sorted(groups.items(),
                                          key=lambda kv: str(kv[0])):
        entry = cache.ingest(records, fp, mesh, dk)
        if entry is not None:
            log(f"cache ingest: {fp} mesh={mesh} device={dk} -> "
                f"winner {entry['winner']} "
                f"(score {entry['score'].get('busy_fraction')} busy, "
                f"{len(records)} rows)")


PALLAS_TAG = os.environ.get("PALLAS_TAG", "r04")


def run_pallas_validation(timeout=1800):
    """Stage 0: compiled pallas kernels vs XLA on the chip (VERDICT r3
    weak #3) — parity must hold BEFORE the protected bench risks the
    tunnel on a Mosaic bug. Writes docs/pallas_onchip_<PALLAS_TAG>.md."""
    log("stage 0: pallas on-chip validation")
    try:
        r = subprocess.run([sys.executable, "tools/pallas_onchip.py"],
                           timeout=timeout, capture_output=True, text=True,
                           cwd=ROOT)
    except subprocess.TimeoutExpired:
        log("pallas validation TIMED OUT — treating tunnel as unhealthy")
        return "timeout"
    log(f"pallas validation rc={r.returncode}")
    out = at_trial.last_json_line(r.stdout)
    if out is None:
        log(f"no JSON from pallas validation (crash); stderr: "
            f"{r.stderr[-300:]}")
    return out


def main():
    quick = "--quick" in sys.argv
    if not probe():
        sys.exit(2)

    pallas_res = None
    if "--skip-pallas" not in sys.argv:
        pallas_res = run_pallas_validation()
        if pallas_res == "timeout":
            # a timeout IS the wedge signature (round-2 postmortem); the
            # tiny probe is not sufficient clearance after one
            log("aborting: pallas validation timed out (tunnel presumed "
                "wedged)")
            sys.exit(2)
        if pallas_res is None:
            # clean crash (Mosaic lowering bug etc.) — exactly what stage
            # 0 exists to surface; re-probe and continue on the XLA path
            # rather than killing the long-awaited bench run
            if not probe():
                log("aborting: tunnel unhealthy after pallas validation")
                sys.exit(2)
            log("pallas validation crashed but tunnel is healthy — "
                "continuing sweep on the XLA path; fix the kernels")
        elif not pallas_res.get("is_tpu"):
            # jax silently fell back to CPU: the TPU is unreachable for
            # this environment, and every bench subprocess would fall back
            # the same way — PERF.md would publish CPU numbers as TPU
            log("aborting: pallas validation ran on CPU (is_tpu=false) — "
                "the TPU backend is not reachable; refusing to publish "
                "CPU throughput as a TPU sweep")
            sys.exit(2)
        elif not pallas_res.get("all_ok"):
            log("pallas kernels FAILED parity on chip — sweep continues "
                "(bench uses the XLA path), but fix before enabling pallas")

    results = []          # artifact dicts (the PERF.md table rows)
    trial_records = []    # TrialResults (what the tuning cache ingests)

    def record(cfg, timeout=3600, measure=True):
        res = run_bench(cfg, timeout, measure=measure)
        if res is not None:
            results.append(res.artifact)
            trial_records.append(res)
        return res.artifact if res is not None else None

    def cache_size():
        d = os.path.join(ROOT, ".jax_cache")
        total, biggest = 0, 0
        try:
            for fn in os.listdir(d):
                sz = os.path.getsize(os.path.join(d, fn))
                total += sz
                biggest = max(biggest, sz)
        except OSError:
            pass
        return total, biggest

    # 0.5) CACHE WARM — the round-2 TPU-compiled ResNet step fell out of
    # .jax_cache (VERDICT r4 weak #2), so the driver's protected bench
    # would pay the full remote compile inside its watchdog. Run bench.py
    # with NO overrides — the driver's EXACT config (BENCH_K defaults to
    # 8, batch 128) — so both its single-step and k-scan programs land in
    # the cache, then verify a big entry exists before sweeping.
    t_before, b_before = cache_size()
    log(f"stage 0.5: cache warm (driver-default config); .jax_cache "
        f"total={t_before >> 20} MB biggest={b_before >> 20} MB")
    # always run even if a big entry already exists: the warm run doubles
    # as the driver-default (K=8) data row, and on a warm cache it's a
    # cheap cache hit, not a fresh compile. measure=False: this row is
    # the driver's EXACT config — no knob env, no measurement arming
    warm = record({}, timeout=3600, measure=False)
    t_after, b_after = cache_size()
    log(f"cache after warm: total={t_after >> 20} MB "
        f"biggest={b_after >> 20} MB "
        f"({'OK: large TPU entry present' if b_after > 10 << 20 else 'WARN: no >10 MB entry — driver bench may still pay the compile'})")
    if warm is None:
        log("aborting: driver-default warm run failed/timed out")
        sys.exit(2)

    steps = 20
    # pin K: bench.py defaults resnet50 to BENCH_K=8, but the sweep
    # isolates K explicitly per config
    # K1_CONTROL off inside the sweep: BENCH_K=1 is its own isolated row
    # here, so the in-bench control would be redundant tunnel risk (the
    # scrubbed warm run above keeps it — driver parity)
    base = {"BENCH_STEPS": steps, "BENCH_K": 1, "BENCH_K1_CONTROL": 0}
    aborted = False
    # 1) dispatch-vs-compute: K sweep at the round-2 config (b128, already
    #    the cheapest compile; K=1 first so the base step compiles alone;
    #    K=8 is covered by the driver-default warm run above)
    for k in ([1] if quick else [1, 5, 20]):
        if record({**base, "BENCH_K": k}) is None:
            log("aborting sweep (unhealthy run)")
            aborted = True
            break
    else:
        # 2) stem + batch sweep, gradual; 256 ONLY with remat (hard rule).
        #    Both K8 variants (with and without S2D) are kept at each
        #    batch size so S2D's effect is isolated, not confounded with K.
        for cfg in ([] if quick else
                    [{"BENCH_S2D": 1},
                     {"BENCH_S2D": 1, "BENCH_K": 8},
                     {"BENCH_BATCH": 192},
                     {"BENCH_BATCH": 192, "BENCH_K": 8},
                     {"BENCH_BATCH": 192, "BENCH_K": 8, "BENCH_S2D": 1},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1, "BENCH_K": 8},
                     {"BENCH_BATCH": 256, "BENCH_REMAT": 1, "BENCH_K": 8,
                      "BENCH_S2D": 1}]):
            assert not (cfg.get("BENCH_BATCH", 0) >= 256
                        and not cfg.get("BENCH_REMAT")), "banned config"
            if record({**base, **cfg}) is None:
                log("aborting batch sweep (unhealthy run)")
                aborted = True
                break

    # 2.5) whole-loop executor (mxtpu.trainloop, PR 6): k-chunked dispatch
    #      + device-side prefetch + per-micro-step lr. Same scan program
    #      family as BENCH_K (cache-friendly), plus the io.*/trainloop.*
    #      telemetry lands in the BENCH json; pallas selection rides the
    #      on-TPU defaults.
    if not aborted:
        for cfg in ([{"BENCH_LOOP_CHUNK": 8}] if quick else
                    [{"BENCH_LOOP_CHUNK": 8},
                     {"BENCH_LOOP_CHUNK": 8, "BENCH_S2D": 1}]):
            if record({**base, **cfg}) is None:
                log("aborting trainloop stage (unhealthy run)")
                aborted = True
                break

    # 3) model stage: BERT (BASELINE config 2; first-ever chip number —
    #    VERDICT r3 next-step #4) then transformer_lm (the causal-LM
    #    family's first chip number). Flash attention pays in both;
    #    default batches from bench.py, one K variant each. HARD RULE:
    #    any earlier timeout means the tunnel is presumed unhealthy — a
    #    fresh large-model compile on a sick tunnel is exactly the
    #    round-2 wedge; the tiny probe is not sufficient clearance after
    #    an abort.
    if results and not aborted and probe():
        for cfg in ([{"BENCH_MODEL": "bert"}] if quick else
                    [{"BENCH_MODEL": "bert"},
                     {"BENCH_MODEL": "bert", "BENCH_K": 8},
                     {"BENCH_MODEL": "transformer_lm"},
                     {"BENCH_MODEL": "transformer_lm", "BENCH_K": 8}]):
            if record({**base, **cfg}) is None:
                log("aborting model stage (unhealthy run)")
                break

    if not results:
        log("no successful runs")
        sys.exit(1)

    resnet = [r for r in results if "BENCH_MODEL" not in r["_config"]]
    bert = [r for r in results if "bert" in r["_config"]]
    best = max(resnet, key=lambda r: r["value"]) if resnet else results[0]
    lines = [
        "# PERF — TPU sweep (one v5e chip via axon tunnel)",
        "",
        f"Sweep of {time.strftime('%Y-%m-%d %H:%M')} — fused train steps,",
        "bf16, numbers from `bench.py` subprocess runs (the driver's exact",
        "path; compiles cached in `.jax_cache`). `k` = micro-steps",
        "dispatched as ONE XLA program (`FusedTrainStep.run_k`); wall",
        "includes per-run process startup.",
        "",
        "| config | value | unit | MFU | busy | wall (s) |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        e = r.get("extra", {})
        bf = (e.get("devicescope") or {}).get("busy_fraction")
        busy = f"{bf:.1%}" if isinstance(bf, (int, float)) else "-"
        lines.append(f"| {r['_config']} | {r['value']} | {r['unit']} | "
                     f"{e.get('mfu', '?')} | {busy} | {r['_wall_s']} |")
    lines += [
        "",
        f"**Best ResNet-50: {best['_config']} → {best['value']} img/s "
        f"(MFU {best.get('extra', {}).get('mfu')})**",
    ]
    if bert:
        bb = max(bert, key=lambda r: r["value"])
        lines.append(f"**BERT: {bb['_config']} → {bb['value']} "
                     f"{bb['unit']} (MFU "
                     f"{bb.get('extra', {}).get('mfu')})**")
    lm = [r for r in results if "transformer_lm" in r["_config"]]
    if lm:
        lb = max(lm, key=lambda r: r["value"])
        lines.append(f"**TransformerLM: {lb['_config']} → {lb['value']} "
                     f"{lb['unit']} (MFU "
                     f"{lb.get('extra', {}).get('mfu')})**")
    if pallas_res is not None:
        lines += ["",
                  "Pallas on-chip validation: "
                  + ("ALL OK" if pallas_res.get("all_ok") else "FAILURES")
                  + f" — see docs/pallas_onchip_{PALLAS_TAG}.md for the "
                  "parity and kernel-vs-XLA timing table."]
    lines += [
        "",
        "Protocol notes: tunnel probed with a 60 s matmul+fetch before the",
        "sweep; batch 256 runs only with remat (a 256-no-remat compile",
        "wedged the shared tunnel in round 2 and is banned); host value",
        "fetch is the only true barrier through the relay, so every timed",
        "segment ends in one.",
    ]
    with open(os.path.join(ROOT, "PERF.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    log(f"PERF.md written; best = {best['_config']} @ {best['value']}")

    # sweep rows ARE trial records (the rebase's point): ingest each
    # (model, batch, dtype, mesh, device-kind) group's best into the
    # tuning cache, so a driver run with MXTPU_AUTOTUNE=1 starts from
    # the sweep's winner with ZERO trials. Only measured rows with an
    # explicit knob config participate (the driver-parity warm run
    # pins no knobs — there is nothing to cache).
    _ingest_into_cache(trial_records)
    print(json.dumps({"best": best}, indent=2))


if __name__ == "__main__":
    main()
