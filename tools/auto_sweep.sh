#!/bin/bash
# One-shot sweep reactor (round 4). Probes the axon tunnel every 10 min
# with the tiny matmul + host fetch; on the FIRST healthy probe it runs
# the full perf protocol — tools/perf_sweep.py (stage 0 = pallas on-chip
# validation, then the resnet K/S2D/batch sweep, then BERT) — appends
# everything to the log, and exits so the tunnel is left alone afterwards
# (round-2 postmortem: never leave anything racing the driver's protected
# bench run).
LOG=${1:-/root/repo/docs/AUTOSWEEP_r04.log}
cd /root/repo || exit 1
echo "$(date -u +%F' '%T) auto_sweep armed (pid $$)" >> "$LOG"
# mxlint static gate FIRST (seconds, no backend): zero findings on the
# tree gates the sweep — a knob read bypassing the resolution order
# would make every sweep row's config untrustworthy
if timeout 300 python tools/mxlint.py --check >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) mxlint gate OK (0 findings)" >> "$LOG"
else
  echo "$(date -u +%F' '%T) mxlint gate FAILED — tree has findings; aborting (fix or suppress with a reason)" >> "$LOG"
  exit 1
fi
# mxlint strict-mode smoke (CPU lenet under MXTPU_STRICT=1): zero
# transfer-guard trips + zero steady-state recompiles, trace_check-valid
if timeout 900 bash tools/mxlint_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) mxlint smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) mxlint smoke FAILED (continuing; steady-loop hygiene suspect)" >> "$LOG"
fi
# CPU-side observability smoke BEFORE touching the tunnel (see
# tools/diag_smoke.sh): a broken telemetry pipeline should fail here,
# not midway through the on-chip sweep.
if timeout 900 bash tools/diag_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) diag smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) diag smoke FAILED (continuing; sweep telemetry suspect)" >> "$LOG"
fi
# serving-path smoke (CPU-only): the inference stack must validate
# before the sweep burns tunnel time
if timeout 900 bash tools/serve_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) serve smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) serve smoke FAILED (continuing; serving path suspect)" >> "$LOG"
fi
# fleet smoke (CPU-only): continuous batching + draining deploys +
# spawned 2-replica fleet artifacts must validate before the sweep
if timeout 1200 bash tools/fleet_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) fleet smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) fleet smoke FAILED (continuing; fleet path suspect)" >> "$LOG"
fi
# healthmon smoke (CPU-only 2-proc cluster + overhead budget): the
# cross-rank health layer must validate before any distributed sweep
if timeout 1200 bash tools/health_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) health smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) health smoke FAILED (continuing; healthmon suspect)" >> "$LOG"
fi
# whole-loop executor smoke (CPU-only): the trainloop + prefetcher +
# telemetry pipeline must hold before sweeping it on the tunnel
if timeout 900 bash tools/trainloop_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) trainloop smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) trainloop smoke FAILED (continuing; whole-loop executor suspect)" >> "$LOG"
fi
# ingest-pipeline smoke (CPU-only): the staged prefetcher's overlap
# win + starvation attribution must validate before sweeping any
# data-path configuration on the tunnel
if timeout 1200 bash tools/io_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) io smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) io smoke FAILED (continuing; ingest pipeline suspect)" >> "$LOG"
fi
# perfscope smoke (CPU-only): decomposition + roofline verdicts + the
# perf_regress gate must validate before any on-chip number is trusted
if timeout 900 bash tools/perfscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) perfscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) perfscope smoke FAILED (continuing; perf attribution suspect)" >> "$LOG"
fi
# sharding smoke (CPU-only 4-fake-device mesh matrix): dp/mp/fsdp loss
# parity + sharding.* telemetry must hold before any pod-layout sweep
if timeout 1800 bash tools/shard_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) shard smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) shard smoke FAILED (continuing; sharded executor suspect)" >> "$LOG"
fi
# commscope smoke (CPU-only fsdp4 mesh): the collective inventory +
# resharding detector + estimated step-budget provenance must hold
# before trusting any sharded layout's attribution
if timeout 900 bash tools/comms_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) comms smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) comms smoke FAILED (continuing; collective observability suspect)" >> "$LOG"
fi
# devicescope smoke (CPU-only): the measured device-timeline window +
# reconciliation the sweep's MFU claims are checked against
if timeout 1200 bash tools/devicescope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) devicescope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) devicescope smoke FAILED (continuing; measured device timeline suspect)" >> "$LOG"
fi
# servescope smoke (CPU-only 64-client load sweep): the serving-path
# attribution + saturation knee + p99 regression gate must validate
# before any serving number is trusted
if timeout 1200 bash tools/servescope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) servescope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) servescope smoke FAILED (continuing; serving attribution suspect)" >> "$LOG"
fi
# resilience smoke (CPU-only chaos harness + resilient bench): NaN
# rollback, torn-checkpoint fallback, stall restart, and elastic
# rank kill/re-join must all SELF-HEAL with the recovery on every
# telemetry surface before any long run is trusted to survive one
if timeout 1800 bash tools/resilience_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) resilience smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) resilience smoke FAILED (continuing; self-healing suspect)" >> "$LOG"
fi
# autotune smoke (CPU-only): the knob tuner's search/cache/provenance
# contracts must hold before the sweep's rows feed the tuning cache
if timeout 1800 bash tools/autotune_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) autotune smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) autotune smoke FAILED (continuing; knob tuner suspect)" >> "$LOG"
fi
# memscope smoke (CPU-only): static footprints joined to rooflines,
# bounded watermark ring, headroom verdict, and the autotuner's
# memory-feasibility pruner rejecting an over-capacity batch candidate
# pre-trial (reason=memory, zero subprocess spent)
if timeout 1800 bash tools/memscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) memscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) memscope smoke FAILED (continuing; memory observability suspect)" >> "$LOG"
fi
# embedding smoke (CPU-only mp4 mesh): 50 recsys/DLRM steps with the
# vocab-sharded tables, dedup lookup, and row-sparse AdaGrad — loss
# must fall, per-device table bytes must beat replicated, the lookup
# collective must attribute to the mp axis, and the resharding
# detector must stay quiet on the annotated layout
if timeout 1200 bash tools/embedding_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) embedding smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) embedding smoke FAILED (continuing; embedding subsystem suspect)" >> "$LOG"
fi
# fleetscope smoke (CPU-only 2-replica spawned fleet): every request
# carries a client-minted traceparent end to end — >= 95% of traces
# must join router-to-replica, the wire-gap + replica-span accounting
# must reconstruct the router e2e, the collector must pull every
# replica with a bounded clock offset, and mxdiag trace/pod must
# render the merged story from the artifacts alone
if timeout 1200 bash tools/fleetscope_smoke.sh >> "$LOG" 2>&1; then
  echo "$(date -u +%F' '%T) fleetscope smoke OK" >> "$LOG"
else
  echo "$(date -u +%F' '%T) fleetscope smoke FAILED (continuing; cross-process tracing suspect)" >> "$LOG"
fi
while true; do
  ts=$(date -u +%H:%M)
  timeout 300 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
print(float((x @ x).sum()))
" >/dev/null 2>&1
  rc=$?
  echo "$ts probe rc=$rc" >> "$LOG"
  if [ "$rc" = "0" ]; then
    echo "$ts TUNNEL HEALTHY -> perf_sweep" >> "$LOG"
    timeout 21600 python tools/perf_sweep.py >> "$LOG" 2>&1
    echo "$(date -u +%F' '%T) perf_sweep rc=$?" >> "$LOG"
    # regression gate over the repo's BENCH trajectory: every sweep run
    # ends with a machine verdict (env_failure artifacts skipped)
    timeout 120 python tools/perf_regress.py --dir . >> "$LOG" 2>&1
    echo "$(date -u +%F' '%T) perf_regress rc=$?; auto_sweep exiting" >> "$LOG"
    exit 0
  fi
  sleep 600
done
