#!/usr/bin/env python
"""On-chip pallas kernel validation (VERDICT r3 weak #3).

The pallas flash-attention and fused-layernorm kernels have only ever run
interpret=True on CPU; this script runs them compiled on the real TPU,
checks numerical parity against the XLA fallback path, and times both
(host-fetch barriers — block_until_ready does not synchronize through the
axon relay). Small shapes on purpose: the point is "the Mosaic lowering is
correct and not slower", measured safely before the protected bench run.

Writes docs/pallas_onchip_<tag>.md and prints one JSON line.

Run only after tools/perf_sweep.py's probe says the tunnel is healthy
(perf_sweep runs this automatically as its stage 0).
"""
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def log(msg):
    print(f"pallas[{time.strftime('%H:%M:%S')}]: {msg}", flush=True)


def fetch(x):
    """The only true barrier through the relay is a host value fetch."""
    import numpy as np
    return float(np.asarray(x).ravel()[0])


def time_fn(fn, *args, iters=20):
    import numpy as np
    out = fn(*args)          # compile
    fetch(out[0] if isinstance(out, tuple) else out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    fetch(out[0] if isinstance(out, tuple) else out)
    return (time.time() - t0) / iters * 1e3  # ms


def main():
    tag = os.environ.get("PALLAS_TAG", "r04")
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    log(f"device: {dev.platform} {getattr(dev, 'device_kind', '?')}")

    from incubator_mxnet_tpu.ops.pallas import is_tpu
    from incubator_mxnet_tpu.ops.pallas.flash_attention import \
        flash_attention
    from incubator_mxnet_tpu.ops.pallas.layer_norm import layer_norm

    log(f"is_tpu() reports: {is_tpu()}")
    rows = []
    results = {"device": str(dev), "is_tpu": bool(is_tpu())}

    # ---- flash attention: (B, H, L, D) bf16, causal + non-causal --------
    rng = np.random.RandomState(0)
    # PALLAS_L/PALLAS_NC shrink the shapes for CPU interpret-mode smokes
    B, H, L, D = 1, 4, int(os.environ.get("PALLAS_L", "512")), 64
    q = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)

    def xla_attn(q, k, v, causal):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / np.sqrt(D)
        if causal:
            mask = jnp.tril(jnp.ones((L, L), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    for causal in (False, True):
        name = f"flash_attn_{'causal' if causal else 'full'}_B{B}H{H}L{L}D{D}"
        pl_fwd = jax.jit(lambda q, k, v, c=causal:
                         flash_attention(q, k, v, causal=c))
        xl_fwd = jax.jit(lambda q, k, v, c=causal: xla_attn(q, k, v, c))
        y_pl = np.asarray(pl_fwd(q, k, v), np.float32)
        y_xl = np.asarray(xl_fwd(q, k, v), np.float32)
        err = float(np.max(np.abs(y_pl - y_xl)))
        ok = err < 0.05  # bf16 accumulation tolerance
        t_pl = time_fn(pl_fwd, q, k, v)
        t_xl = time_fn(xl_fwd, q, k, v)

        # backward parity + timing
        def loss_pl(q, k, v, c=causal):
            return flash_attention(q, k, v, causal=c).astype(
                jnp.float32).sum()

        def loss_xl(q, k, v, c=causal):
            return xla_attn(q, k, v, c).astype(jnp.float32).sum()
        # ALL grads: the backward is two kernels (dq; dk/dv) — checking
        # only dq would pass with a broken dk/dv kernel
        g_pl = jax.jit(jax.grad(loss_pl, argnums=(0, 1, 2)))
        g_xl = jax.jit(jax.grad(loss_xl, argnums=(0, 1, 2)))
        gs_pl = [np.asarray(g, np.float32) for g in g_pl(q, k, v)]
        gs_xl = [np.asarray(g, np.float32) for g in g_xl(q, k, v)]
        # relative: grad magnitudes grow with L (dv sums over queries)
        gerr = max(float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-6))
                   for a, b in zip(gs_pl, gs_xl))
        gok = gerr < 0.02
        tb_pl = time_fn(g_pl, q, k, v)
        tb_xl = time_fn(g_xl, q, k, v)
        rows.append((name, ok and gok, err, gerr, t_pl, t_xl, tb_pl, tb_xl))
        log(f"{name}: fwd_err={err:.4f} bwd_err={gerr:.4f} "
            f"fwd {t_pl:.2f}ms vs xla {t_xl:.2f}ms; "
            f"bwd {tb_pl:.2f}ms vs xla {tb_xl:.2f}ms "
            f"{'OK' if ok and gok else 'FAIL'}")

    # ---- fused layernorm: (4096, 1024) bf16 -----------------------------
    N, C = (4096, 1024) if "PALLAS_NC" not in os.environ else \
        tuple(int(s) for s in os.environ["PALLAS_NC"].split("x"))
    x = jnp.asarray(rng.randn(N, C), jnp.bfloat16)
    gmm = jnp.asarray(rng.rand(C) + 0.5, jnp.float32)
    bt = jnp.asarray(rng.randn(C) * 0.1, jnp.float32)

    def xla_ln(x, g, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + 1e-5) * g + b).astype(
            x.dtype)

    pl_ln = jax.jit(lambda x, g, b: layer_norm(x, g, b))
    xl_ln = jax.jit(xla_ln)
    y_pl = np.asarray(pl_ln(x, gmm, bt), np.float32)
    y_xl = np.asarray(xl_ln(x, gmm, bt), np.float32)
    err = float(np.max(np.abs(y_pl - y_xl)))
    ok = err < 0.05
    t_pl = time_fn(pl_ln, x, gmm, bt)
    t_xl = time_fn(xl_ln, x, gmm, bt)

    def l_pl(x, g, b):
        return layer_norm(x, g, b).astype(jnp.float32).sum()

    def l_xl(x, g, b):
        return xla_ln(x, g, b).astype(jnp.float32).sum()
    gp = jax.jit(jax.grad(l_pl, argnums=(0, 1, 2)))   # dx, dgamma, dbeta
    gx = jax.jit(jax.grad(l_xl, argnums=(0, 1, 2)))
    gerr = max(float(np.max(np.abs(np.asarray(a, np.float32)
                                   - np.asarray(b, np.float32)))
                     / (np.max(np.abs(np.asarray(b, np.float32))) + 1e-6))
               for a, b in zip(gp(x, gmm, bt), gx(x, gmm, bt)))
    gok = gerr < 0.02
    tb_pl = time_fn(gp, x, gmm, bt)
    tb_xl = time_fn(gx, x, gmm, bt)
    rows.append((f"layer_norm_{N}x{C}", ok and gok, err, gerr,
                 t_pl, t_xl, tb_pl, tb_xl))
    log(f"layer_norm: fwd_err={err:.4f} bwd_err={gerr:.4f} "
        f"fwd {t_pl:.2f}ms vs xla {t_xl:.2f}ms "
        f"{'OK' if ok and gok else 'FAIL'}")

    all_ok = all(r[1] for r in rows)
    results["all_ok"] = all_ok
    results["rows"] = [
        {"case": r[0], "ok": r[1], "fwd_err": r[2], "bwd_err": r[3],
         "pallas_fwd_ms": round(r[4], 3), "xla_fwd_ms": round(r[5], 3),
         "pallas_bwd_ms": round(r[6], 3), "xla_bwd_ms": round(r[7], 3)}
        for r in rows]

    md = ["# Pallas on-chip validation — %s" % tag, "",
          f"Device: `{dev}` ({time.strftime('%Y-%m-%d %H:%M')} UTC). "
          "Compiled (non-interpret) kernels vs the XLA fallback path; "
          "timings are means of 20 iterations bounded by host fetches.",
          "",
          "| case | parity | fwd err | bwd err | pallas fwd (ms) | "
          "xla fwd (ms) | pallas bwd (ms) | xla bwd (ms) |",
          "|---|---|---|---|---|---|---|---|"]
    for r in rows:
        md.append("| %s | %s | %.4f | %.4f | %.2f | %.2f | %.2f | %.2f |"
                  % (r[0], "OK" if r[1] else "FAIL", r[2], r[3], r[4],
                     r[5], r[6], r[7]))
    md += ["",
           "Decision rule: the fused step uses the pallas path only where "
           "it beats XLA here; a FAIL or slower kernel keeps the XLA path "
           "(documented, not silent)."]
    out_path = os.path.join(ROOT, "docs", f"pallas_onchip_{tag}.md")
    with open(out_path, "w") as f:
        f.write("\n".join(md) + "\n")
    log(f"wrote {out_path}")
    print(json.dumps(results))
    sys.exit(0 if all_ok else 1)


if __name__ == "__main__":
    main()
