#!/bin/bash
# mxlint smoke (CPU-only, no tunnel time): the PR 14 acceptance gate.
#
# 1. static: `tools/mxlint.py --check` must exit 0 on the tree (zero
#    findings — every knob read routed/allowlisted, no counter drift,
#    never-raise modules clean), and the bad fixtures must still FIRE
#    (a linter that stopped seeing violations is worse than none).
# 2. strict-mode runtime: a 50-step CPU lenet bench under MXTPU_STRICT=1
#    completes with ZERO transfer-guard trips, ZERO steady-state
#    recompiles and ZERO donation violations, every steady dispatch
#    guarded, validated by trace_check's check_mxlint_extra.
# 3. renderers: `mxdiag.py lint` renders the findings report.
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
OUT=${MXLINT_SMOKE_OUT:-/tmp/mxtpu_mxlint_smoke}
rm -rf "$OUT"; mkdir -p "$OUT"
fail() { echo "mxlint_smoke: FAIL: $*" >&2; exit 1; }

echo "== mxlint smoke: static gate =="
python tools/mxlint.py --check > "$OUT/lint.txt" 2>&1 \
  || { cat "$OUT/lint.txt"; fail "tree has mxlint findings"; }
grep -q "0 findings" "$OUT/lint.txt" || fail "gate output malformed"

# the linter must still catch the bad fixtures (tier-1 runs the full
# matrix; the smoke spot-checks one rule end-to-end through the CLI)
mkdir -p "$OUT/pkg/incubator_mxnet_tpu"
cp tests/fixtures/mxlint/raw_env_read_bad.py "$OUT/pkg/incubator_mxnet_tpu/"
python tools/mxlint.py --check "$OUT/pkg" > "$OUT/fixture.txt" 2>&1
[ $? -eq 1 ] || fail "bad fixture not caught by the CLI"
grep -q "raw-env-read" "$OUT/fixture.txt" || fail "rule id missing"

echo "== mxlint smoke: strict-mode lenet (MXTPU_STRICT=1) =="
MXTPU_STRICT=1 BENCH_MODEL=lenet BENCH_STEPS=50 BENCH_DTYPE=float32 \
  timeout 600 python bench.py > "$OUT/bench_raw.txt" 2> "$OUT/bench.err" \
  || { tail -5 "$OUT/bench.err"; fail "strict bench run failed"; }
tail -1 "$OUT/bench_raw.txt" > "$OUT/bench.json"

python - "$OUT/bench.json" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
mx = doc.get("extra", {}).get("mxlint")
assert isinstance(mx, dict), f"no extra.mxlint in strict bench: {doc.keys()}"
assert mx.get("strict") is True, mx
assert mx["transfer_guard_trips"] == 0, f"host syncs leaked into the steady loop: {mx}"
assert mx["recompiles"] == 0, f"steady-state recompiles: {mx['recompiled_programs']}"
assert mx["donation_violations"] == 0, mx
assert mx["findings"] == 0, mx
assert mx["guarded_dispatches"] >= 50, f"steady loop not guarded: {mx}"
assert doc.get("value", 0) > 0, "no throughput measured"
print(f"strict lenet OK: {mx['guarded_dispatches']} guarded dispatches, "
      f"0 findings, {doc['value']} img/s")
EOF

# the artifact must validate under trace_check (incl. check_mxlint_extra)
python tools/trace_check.py "$OUT/bench.json" || fail "trace_check rejects strict artifact"

echo "== mxlint smoke: renderers =="
python tools/mxdiag.py lint > "$OUT/mxdiag_lint.txt" 2>&1 \
  || fail "mxdiag lint nonzero on a clean tree"
grep -q "tree is clean" "$OUT/mxdiag_lint.txt" || fail "mxdiag lint output malformed"

echo "mxlint_smoke: OK"
