#!/bin/bash
# Tier-1 whole-loop-executor smoke: 50 lenet train steps ON CPU through
# mxtpu.trainloop (BENCH_LOOP_CHUNK chunks of 5 + the device prefetcher),
# then assert from the BENCH json that
#   * the loss went DOWN over the run (the executor actually trains),
#   * the io.* counter family is present (io.wait_ms — starvation is
#     measurable) and io.batches_prefetched advanced,
#   * trainer.dispatches_per_step < 1 (k micro-steps rode one dispatch),
#   * the trainloop.* family is present and consistent (steps == 50).
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_trainloop_smoke_bench.json}
LOG=/tmp/mxtpu_trainloop_smoke.log

echo "trainloop_smoke: 50 lenet steps on CPU via the whole-loop executor"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 \
  BENCH_DTYPE=float32 BENCH_LOOP_CHUNK=5 BENCH_K1_CONTROL=0 \
  BENCH_TRACE_FILE=/tmp/mxtpu_trainloop_smoke_trace.json \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "trainloop_smoke: bench.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
extra = doc.get("extra") or {}
assert extra.get("loop_chunk") == 5, f"loop_chunk={extra.get('loop_chunk')}"
assert extra.get("steps") == 50, f"steps={extra.get('steps')}"
assert isinstance(extra.get("mfu"), (int, float)), "no MFU in BENCH json"
c = extra.get("counters") or {}
for name in ("io/io.wait_ms", "io/io.batches_prefetched", "io/io.depth",
             "trainloop/trainloop.chunks", "trainloop/trainloop.steps"):
    assert name in c, f"counter {name} missing from BENCH json"
assert c["io/io.batches_prefetched"] >= 50, c["io/io.batches_prefetched"]
# >= : the counter also covers the compile/warmup chunk before timing
assert c["trainloop/trainloop.steps"] >= 50, c["trainloop/trainloop.steps"]
dps = extra.get("dispatches_per_step")
assert dps is not None and dps < 1, \
    f"dispatches_per_step={dps} (whole-loop executor should be < 1)"
# loss must decrease: final vs the first compiled step's magnitude.
# lenet@64 starts near ln(10)≈2.3; after 50 sgd steps it must be lower.
final = extra.get("final_loss")
assert final is not None and final < 2.0, \
    f"final_loss={final} — loss did not decrease over 50 steps"
print(f"trainloop_smoke: OK ({doc['value']} {doc['unit']}, "
      f"final_loss={final}, dispatches_per_step={dps}, "
      f"io.wait_ms={round(c['io/io.wait_ms'], 1)})")
EOF

# schema-check the BENCH json itself (MFU field + counter families)
python tools/trace_check.py "$OUT" || exit 1
echo "trainloop_smoke: whole-loop executor pipeline validates"
