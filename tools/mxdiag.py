#!/usr/bin/env python
"""Pretty-print mxtpu diagnostics artifacts.

Flight-recorder dumps (`diagnostics.flight` / `mxtpu_flight_*.json`):
header, env/config snapshot, exception (when the dump came from the crash
path), counter table, and the tail of the event ring with relative
timestamps — the "what happened in the seconds before the crash" view.

Sampler time series (`metrics.jsonl`): first/last sample, counter deltas
and rates over the covered window.

Usage:
    python tools/mxdiag.py DUMP.json [--events N]
    python tools/mxdiag.py metrics.jsonl
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0


def _fmt_ts(epoch) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(float(epoch)))
    except (TypeError, ValueError):
        return str(epoch)


def print_flight(doc: dict, n_events: int) -> None:
    print(f"flight dump  schema={doc.get('schema')}  "
          f"reason={doc.get('reason')!r}")
    print(f"  dumped at {_fmt_ts(doc.get('dumped_at'))}  "
          f"(recorder started {_fmt_ts(doc.get('started_at'))})")
    env = doc.get("env") or {}
    print(f"  pid {env.get('pid')}  python {env.get('python')}  "
          f"jax backend {env.get('jax_backend')} "
          f"x{env.get('jax_device_count')}  "
          f"mxtpu {env.get('mxtpu_version')}")
    if env.get("argv"):
        print(f"  argv: {' '.join(env['argv'])}")
    for k, v in sorted((env.get("env") or {}).items()):
        print(f"    {k}={v}")
    cfg = doc.get("config") or {}
    if cfg:
        print("  config: " + ", ".join(f"{k}={v}"
                                       for k, v in sorted(cfg.items())))
    exc = doc.get("exception")
    if exc:
        print(f"\n  EXCEPTION: {exc.get('type')}: {exc.get('message')}")
        for frame in exc.get("traceback") or []:
            for ln in frame.rstrip().splitlines():
                print("    " + ln)
    counters = doc.get("counters") or {}
    kinds = doc.get("counter_kinds") or {}
    if counters:
        print(f"\n  counters ({len(counters)}):")
        width = max(len(k) for k in counters)
        for k in sorted(counters):
            v = counters[k]
            tag = kinds.get(k, "?")[0]
            shown = _fmt_bytes(v) if k.endswith("_bytes") or \
                k.endswith("/current_bytes") or "bytes" in k else v
            print(f"    [{tag}] {k:<{width}}  {shown}")
    events = doc.get("events") or []
    tail = events[-n_events:]
    t_end = doc.get("dumped_at") or (tail[-1]["ts"] if tail else 0)
    print(f"\n  events: {len(events)} in ring "
          f"(capacity {doc.get('capacity')}), last {len(tail)}:")
    for ev in tail:
        dt = ev.get("ts", 0) - t_end
        args = ev.get("args")
        extra = "  " + json.dumps(args) if args else ""
        print(f"    {dt:>+9.3f}s  {ev.get('kind', '?'):<10} "
              f"{ev.get('name', '?')}{extra}")


def print_metrics(path: str) -> None:
    samples = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                samples.append(json.loads(ln))
    if not samples:
        print(f"{path}: no samples")
        return
    first, last = samples[0], samples[-1]
    span = last["ts"] - first["ts"]
    print(f"metrics series: {len(samples)} samples over {span:.2f}s "
          f"({_fmt_ts(first['ts'])} .. {_fmt_ts(last['ts'])})")
    kinds = last.get("kinds") or {}
    names = sorted(set(first.get("counters", {})) |
                   set(last.get("counters", {})))
    width = max((len(n) for n in names), default=4)
    for name in names:
        a = first.get("counters", {}).get(name)
        b = last.get("counters", {}).get(name)
        kind = kinds.get(name, "?")
        if kind == "counter" and isinstance(a, (int, float)) \
                and isinstance(b, (int, float)):
            rate = (b - a) / span if span > 0 else 0.0
            print(f"  [c] {name:<{width}}  {a} -> {b}  "
                  f"(+{b - a}, {rate:.2f}/s)")
        else:
            print(f"  [{kind[0]}] {name:<{width}}  {b}")
    mem = last.get("memory")
    if mem:
        print(f"  memory: current {_fmt_bytes(mem.get('current_bytes'))}  "
              f"peak {_fmt_bytes(mem.get('peak_bytes'))}  "
              f"live {mem.get('live_arrays')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="flight dump .json or metrics .jsonl")
    ap.add_argument("--events", type=int, default=40,
                    help="how many trailing ring events to print")
    args = ap.parse_args(argv)
    if args.path.endswith(".jsonl"):
        print_metrics(args.path)
        return 0
    try:
        with open(args.path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"{args.path}: {e}", file=sys.stderr)
        return 1
    if isinstance(doc, dict) and str(doc.get("schema", "")).startswith(
            "mxtpu.flight/"):
        print_flight(doc, args.events)
        return 0
    print(f"{args.path}: not a flight dump (schema="
          f"{doc.get('schema') if isinstance(doc, dict) else None!r}); "
          f"for Chrome traces use chrome://tracing", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
