#!/usr/bin/env python
"""Pretty-print mxtpu diagnostics artifacts.

Flight-recorder dumps (`diagnostics.flight` / `mxtpu_flight_*.json`):
header, env/config snapshot, exception (when the dump came from the crash
path), counter table, and the tail of the event ring with relative
timestamps — the "what happened in the seconds before the crash" view.

Sampler time series (`metrics.jsonl`): first/last sample, counter deltas
and rates over the covered window.

`merge`: interleave SEVERAL ranks' flight dumps and/or structured event
logs (`mxtpu.events/` JSONL) into one time-ordered cross-rank timeline,
each line tagged with its rank — the post-mortem view for distributed
failures ("rank 1 went quiet 40 s before rank 0's collective timed
out"). `-o merged.jsonl` additionally writes the merged timeline as
`mxtpu.events/2` records (validated by tools/trace_check.py), carrying
each record's `mono` companion through when present.

`perf`: the MFU-decomposition report from a BENCH json
(`extra.perfscope`) — step budget with per-component shares (the
`collective` row carries its provenance: measured / estimated /
unavailable), counterfactual MFU table, per-program roofline verdicts.

`comms`: the collective-inventory report from a BENCH json
(`extra.commscope`) — per compiled program, one row per (op kind, mesh
axis) with count / payload MiB / analytic ICI estimate, plus any
resharding findings with the offending operand shapes.

`device`: the measured device-timeline report from a BENCH json
(`extra.devicescope`) — busy fraction, top-K device ops joined to their
roofline verdicts, measured collective lanes, idle-gap taxonomy, and
the analytic-vs-measured reconciliation.

`serve`: the tail-latency attribution report from a BENCH json
(`extra.servescope` / `extra.serve_load`) — the ramp sweep with its
saturation knee, per-bucket p99 cohort attribution (queue_wait /
coalesce_delay / pad_overhead / device_exec / respond) with roofline +
resharding verdicts, and the one-line advice ("p99 is 83% queue_wait
at bucket 128 - raise max_batch, not the kernel").

`fleet`: the replica-fleet report from a serve_load ``--fleet`` BENCH
json (`extra.fleet`) — per-replica dispatch table with client-observed
tails, the dispatch-imbalance ratio, the shared compile-cache verdict
(replica N+1's warmup: hit or recompile?), and the drain/swap/readmit
deploy timeline from the events log.

`mem`: the memory report from a BENCH json (`extra.memscope`) — the
static per-program footprint table joined to the roofline verdicts
(largest peak flagged), the watermark ring's p50/p95/peak with a tail
sparkline, the capacity/headroom verdict, the FSDP
analytic-vs-measured reconciliation, and the OOM post-mortem when the
run died of RESOURCE_EXHAUSTED.

`io`: the ingest-pipeline report from a BENCH json (`extra.io`) —
pipeline geometry (decode workers, buffer depth), cumulative per-stage
walls (read / decode / reorder / put), the consumer's empty-buffer
wait, and devicescope's measured input-starvation split with the
one-line triage ("starved 31% of idle: 80% decode → raise io_workers,
not prefetch depth").

`trace`: ONE request's cross-process span tree, joined on the
fleetscope `trace_id` across event logs from different processes — the
router's `fleetscope.request` record (admit → forward → respond) over
the replica's `serving.request` span (queue_wait / coalesce_delay /
pad_overhead / device_exec / respond), with the **wire gap** (router
forward wall minus replica e2e — a difference of perf_counter
durations, so clock skew cannot enter it) explicit between them, and
the `serving.batch` record the request coalesced into.

`pod`: the fleet-wide trace aggregate from a serve_load --fleet BENCH
json (`extra.fleetscope`) — join accounting (client-minted / sampled /
joined, unjoined forwards counted), wire-gap percentiles, the
per-replica trace table with straggler flags (report-only context for
the router's least-loaded score), and the collector's per-process
clock-offset estimates ± rtt/2.

`tune`: the autotune report from a BENCH json (`extra.autotune`) —
cache hit/miss verdict, the trial table with measured busy fraction /
step wall / MFU / score provenance per config, the pruning reasons
(which knob families the measured gap taxonomy cut), and the
winner-vs-default delta.

Usage:
    python tools/mxdiag.py DUMP.json [--events N]
    python tools/mxdiag.py metrics.jsonl
    python tools/mxdiag.py perf BENCH.json
    python tools/mxdiag.py comms BENCH.json
    python tools/mxdiag.py device BENCH.json
    python tools/mxdiag.py mem BENCH.json
    python tools/mxdiag.py io BENCH.json
    python tools/mxdiag.py serve BENCH.json
    python tools/mxdiag.py fleet BENCH.json [--events EVENTS.jsonl]
    python tools/mxdiag.py tune BENCH.json
    python tools/mxdiag.py trace TRACE_ID events.jsonl \\
        events_replica_*.jsonl
    python tools/mxdiag.py pod BENCH.json
    python tools/mxdiag.py merge events_rank0.jsonl events_rank1.jsonl \\
        mxtpu_flight_123.json [-o merged.jsonl] [--tail N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0


def _fmt_ts(epoch) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(float(epoch)))
    except (TypeError, ValueError):
        return str(epoch)


def _fmt_flops(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000 or unit == "P":
            return f"{n:,.2f} {unit}FLOP"
        n /= 1000.0


def _fmt_cost_args(args: dict) -> str:
    """Human rendering of a perfscope-enriched compile span's cost
    fields (flops / bytes_accessed / roofline / ai)."""
    parts = []
    if args.get("flops") is not None:
        parts.append(_fmt_flops(args["flops"]))
    if args.get("bytes_accessed") is not None:
        parts.append(_fmt_bytes(args["bytes_accessed"]))
    if args.get("ai") is not None:
        parts.append(f"AI {args['ai']:.2f}")
    if args.get("roofline"):
        parts.append(f"-> {args['roofline'].upper()}")
    rest = {k: v for k, v in args.items()
            if k not in ("flops", "bytes_accessed", "ai", "roofline",
                         "est_compute_ms", "est_memory_ms")}
    out = "  " + "  ".join(parts)
    if rest:
        out += "  " + json.dumps(rest)
    return out


def print_flight(doc: dict, n_events: int) -> None:
    print(f"flight dump  schema={doc.get('schema')}  "
          f"reason={doc.get('reason')!r}")
    print(f"  dumped at {_fmt_ts(doc.get('dumped_at'))}  "
          f"(recorder started {_fmt_ts(doc.get('started_at'))})")
    env = doc.get("env") or {}
    print(f"  pid {env.get('pid')}  python {env.get('python')}  "
          f"jax backend {env.get('jax_backend')} "
          f"x{env.get('jax_device_count')}  "
          f"mxtpu {env.get('mxtpu_version')}")
    if env.get("argv"):
        print(f"  argv: {' '.join(env['argv'])}")
    for k, v in sorted((env.get("env") or {}).items()):
        print(f"    {k}={v}")
    cfg = doc.get("config") or {}
    if cfg:
        print("  config: " + ", ".join(f"{k}={v}"
                                       for k, v in sorted(cfg.items())))
    exc = doc.get("exception")
    if exc:
        print(f"\n  EXCEPTION: {exc.get('type')}: {exc.get('message')}")
        for frame in exc.get("traceback") or []:
            for ln in frame.rstrip().splitlines():
                print("    " + ln)
    counters = doc.get("counters") or {}
    kinds = doc.get("counter_kinds") or {}
    if counters:
        print(f"\n  counters ({len(counters)}):")
        width = max(len(k) for k in counters)
        for k in sorted(counters):
            v = counters[k]
            tag = kinds.get(k, "?")[0]
            shown = _fmt_bytes(v) if k.endswith("_bytes") or \
                k.endswith("/current_bytes") or "bytes" in k else v
            print(f"    [{tag}] {k:<{width}}  {shown}")
    events = doc.get("events") or []
    tail = events[-n_events:]
    t_end = doc.get("dumped_at") or (tail[-1]["ts"] if tail else 0)
    print(f"\n  events: {len(events)} in ring "
          f"(capacity {doc.get('capacity')}), last {len(tail)}:")
    for ev in tail:
        dt = ev.get("ts", 0) - t_end
        args = ev.get("args")
        if args and ev.get("kind") == "compile" and \
                ("flops" in args or "roofline" in args):
            extra = _fmt_cost_args(args)   # perfscope-enriched span
        else:
            extra = "  " + json.dumps(args) if args else ""
        print(f"    {dt:>+9.3f}s  {ev.get('kind', '?'):<10} "
              f"{ev.get('name', '?')}{extra}")


def print_metrics(path: str) -> None:
    samples = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                samples.append(json.loads(ln))
    if not samples:
        print(f"{path}: no samples")
        return
    first, last = samples[0], samples[-1]
    span = last["ts"] - first["ts"]
    print(f"metrics series: {len(samples)} samples over {span:.2f}s "
          f"({_fmt_ts(first['ts'])} .. {_fmt_ts(last['ts'])})")
    kinds = last.get("kinds") or {}
    names = sorted(set(first.get("counters", {})) |
                   set(last.get("counters", {})))
    width = max((len(n) for n in names), default=4)
    for name in names:
        a = first.get("counters", {}).get(name)
        b = last.get("counters", {}).get(name)
        kind = kinds.get(name, "?")
        if kind == "counter" and isinstance(a, (int, float)) \
                and isinstance(b, (int, float)):
            rate = (b - a) / span if span > 0 else 0.0
            print(f"  [c] {name:<{width}}  {a} -> {b}  "
                  f"(+{b - a}, {rate:.2f}/s)")
        else:
            print(f"  [{kind[0]}] {name:<{width}}  {b}")
    mem = last.get("memory")
    if mem:
        print(f"  memory: current {_fmt_bytes(mem.get('current_bytes'))}  "
              f"peak {_fmt_bytes(mem.get('peak_bytes'))}  "
              f"live {mem.get('live_arrays')}")


# ---------------------------------------------------------------------------
# perf: MFU-decomposition report from a BENCH json (extra.perfscope)
# ---------------------------------------------------------------------------

def _print_reconciliation(recon: dict, indent: str = "  ") -> None:
    """The analytic-vs-measured table a devicescope window produced —
    shared by `perf` and `device` so the two reports can't drift apart
    on the reconciliation schema."""
    ana, mea = recon.get("analytic") or {}, recon.get("measured") or {}
    thr = recon.get("threshold")
    drift = recon.get("drift") or {}
    print(f"\n{indent}analytic vs measured (devicescope window"
          + (f", drift threshold {thr:.0%}" if thr else "") + "):")
    for comp in ("device_compute", "collective"):
        a, m = ana.get(comp + "_ms"), mea.get(comp + "_ms")
        if a is None or m is None:
            continue
        dr = drift.get(comp)
        src = (f"analytic({ana.get('source')})"
               if comp == "device_compute"
               else f"analytic({ana.get('collective_source')})")
        line = (f"{indent}  {comp:<15} measured {m:>10.3f} ms   "
                f"{src} {a:>10.3f} ms")
        if dr is not None:
            line += f"   delta {dr:>6.1%}"
            if thr is not None and dr > thr:
                line += "  << DRIFT"
        print(line)
    if recon.get("drift_warning"):
        print(f"{indent}  DRIFT WARNING: analytic and measured disagree "
              f"beyond the threshold — an estimate (probe / ring model "
              f"/ peak table) has gone stale; trust the measured window "
              f"(docs/devicescope.md)")

def _load_bench(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc and "metric" not in doc:
        doc = doc["parsed"] or {}
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError(f"{path}: not a bench result "
                         f"(no 'metric'/'parsed' key)")
    return doc


def print_perf(doc: dict) -> int:
    """The "why is my MFU low" report: step budget with per-component
    shares, the counterfactual MFU table, and per-program roofline
    verdicts."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')}, batch "
          f"{extra.get('batch')}, {extra.get('dtype')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    ps = extra.get("perfscope")
    if not isinstance(ps, dict):
        print("  no extra.perfscope section (perfscope was off — "
              "rerun without BENCH_PERFSCOPE=0)")
        return 1
    peaks = ps.get("peaks") or {}
    print(f"  peaks: {peaks.get('device_kind')} "
          f"(table row {peaks.get('table_row')})  "
          f"bf16 {_fmt_flops(peaks.get('peak_flops_bf16'))}/s  "
          f"f32 {_fmt_flops(peaks.get('peak_flops_f32'))}/s  "
          f"HBM {_fmt_bytes(peaks.get('hbm_bytes_per_s'))}/s")
    d = ps.get("decomposition")
    if isinstance(d, dict) and d.get("step_ms"):
        step = d["step_ms"]
        recon = d.get("reconciliation") \
            if isinstance(d.get("reconciliation"), dict) else None
        print(f"\n  step budget ({d.get('steps')} steps, source="
              f"{d.get('source')}):  step_ms = {step:.3f}")
        for comp in ("device_compute", "collective", "input_wait",
                     "host_gap", "other"):
            ms = d.get(comp + "_ms")
            if ms is None:
                continue
            share = ms / step if step else 0.0
            bar = "#" * int(round(share * 40))
            tag = ""
            if comp == "collective":
                src = d.get("collective_source")
                if src == "estimated":
                    tag = "  [estimated: commscope static-HLO]"
                elif src == "measured(profile)":
                    tag = "  [measured: devicescope window]"
                elif src == "unavailable":
                    tag = ("  [UNAVAILABLE: in-program collectives, "
                           "commscope off — not a measured zero]")
            print(f"    {comp:<15} {ms:>10.3f} ms  {share:>6.1%}  "
                  f"{bar}{tag}")
        print(f"    {'(coverage':<15} {d.get('coverage')})")
        if recon:
            # BOTH sources exist: show the analytic numbers (probe /
            # ring estimate) beside the measured window, with the delta
            # — never only one source when the run carried both
            _print_reconciliation(recon)
        if d.get("mfu") is not None:
            print(f"\n  MFU decomposition:  achieved {d['mfu']:.4f}")
            if d.get("mfu_device_only") is not None:
                print(f"    device-compute-bound ceiling  "
                      f"{d['mfu_device_only']:.4f}")
            for comp, v in (d.get("mfu_if_removed") or {}).items():
                if v is not None and d["mfu"]:
                    print(f"    if {comp + ' were free:':<22} {v:.4f}  "
                          f"({v / d['mfu']:.2f}x)")
    else:
        print("  no step-time decomposition in this artifact")
    progs = ps.get("programs") or []
    if progs:
        print(f"\n  compiled programs ({len(progs)}):")
        width = max(len(p.get("name", "?")) for p in progs)
        for p in progs:
            f = _fmt_flops(p.get("flops")) if p.get("flops") is not None \
                else "-"
            b = _fmt_bytes(p.get("bytes_accessed")) \
                if p.get("bytes_accessed") is not None else "-"
            ai = f"AI {p['ai']:.2f}" if p.get("ai") is not None else ""
            print(f"    {p.get('name', '?'):<{width}}  "
                  f"{p.get('verdict', '?'):<14} {f:>14}  {b:>12}  {ai}")
    return 0


def _perf_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py perf",
        description="MFU-decomposition report from a BENCH json "
                    "(extra.perfscope)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"perf: {e}", file=sys.stderr)
        return 1
    return print_perf(doc)


# ---------------------------------------------------------------------------
# tune: the autotune report from a BENCH json (extra.autotune)
# ---------------------------------------------------------------------------

def _fmt_busy(bf) -> str:
    return f"{bf:.1%}" if isinstance(bf, (int, float)) else "-"


def _fmt_ms(v) -> str:
    return f"{v:.2f}" if isinstance(v, (int, float)) else "-"


def print_tune(doc: dict) -> int:
    """The "what did the tuner decide and why" report: cache verdict,
    the trial table (config, measured busy, step wall, MFU, score
    provenance), the pruning reasons (which knob families the measured
    gap taxonomy cut, and why), and the winner-vs-default delta."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')}, batch "
          f"{extra.get('batch')}, {extra.get('dtype')})")
    at = extra.get("autotune")
    if not isinstance(at, dict):
        print("  no extra.autotune section (pre-autotune artifact)")
        return 1
    if not at.get("enabled"):
        print("  autotune DISABLED for this run (MXTPU_AUTOTUNE unset)")
        resolved = at.get("resolved")
        if isinstance(resolved, dict):
            print(f"  resolved knobs: "
                  + " ".join(f"{k}={v}" for k, v in resolved.items()))
        return 0
    if at.get("error"):
        print(f"  autotune ERRORED: {at['error']} (run was untuned)")
        return 1
    cache = at.get("cache") or {}
    verdict = "HIT (0 trials — started tuned)" if at.get("cache_hit") \
        else (f"MISS -> searched {at.get('trials')} trial(s)"
              + (", budget exhausted -> best-so-far"
                 if at.get("budget_exhausted") else ""))
    print(f"\n  tuning cache: {verdict}")
    print(f"    key: fingerprint={cache.get('fingerprint')}  "
          f"mesh={cache.get('mesh')}  device={cache.get('device_kind')}")
    if cache.get("rejects"):
        print(f"    {cache['rejects']} stale/corrupt cache entry(ies) "
              f"rejected (counted; re-searched)")
    if at.get("diagnosis"):
        print(f"  baseline diagnosis: {at['diagnosis']}")
    table = at.get("trial_table") or []
    if table:
        print(f"\n  trials ({len(table)}):")
        print(f"    {'move':<24} {'status':<7} {'busy':>7} "
              f"{'step_ms':>9} {'mfu':>8} {'provenance':<18}")
        win = at.get("winner")
        for row in table:
            cfg = row.get("config") or {}
            move = (f"{row['knob']}={row.get('value')}"
                    if row.get("knob") else "baseline (default)")
            mfu = row.get("mfu")
            tag = "  << WINNER" if win and cfg == win else ""
            err = f"  ({str(row.get('error'))[:40]})" \
                if row.get("status") == "failed" else ""
            print(f"    {move:<24} {row.get('status', '?'):<7} "
                  f"{_fmt_busy(row.get('busy_fraction')):>7} "
                  f"{_fmt_ms(row.get('step_ms')):>9} "
                  f"{mfu if isinstance(mfu, (int, float)) else '-':>8} "
                  f"{row.get('provenance') or '-':<18}{tag}{err}")
    pruned = at.get("pruned") or {}
    if pruned:
        print(f"\n  pruned knob families ({len(pruned)}):")
        for k in sorted(pruned):
            print(f"    {k:<15} {pruned[k]}")
    win, sc, df = at.get("winner"), at.get("score"), at.get("default")
    if win:
        print(f"\n  winner: "
              + (" ".join(f"{k}={v}" for k, v in win.items()
                          if v not in (None, False)) or "default"))
    if isinstance(sc, dict):
        line = (f"    score: busy {_fmt_busy(sc.get('busy_fraction'))}  "
                f"step {_fmt_ms(sc.get('step_ms'))} ms  "
                f"mfu {sc.get('mfu')}  [{sc.get('provenance')}]")
        if isinstance(df, dict):
            line += (f"\n    vs default: busy "
                     f"{_fmt_busy(df.get('busy_fraction'))}  "
                     f"step {_fmt_ms(df.get('step_ms'))} ms  "
                     f"mfu {df.get('mfu')}")
            b0, b1 = df.get("busy_fraction"), sc.get("busy_fraction")
            if isinstance(b0, (int, float)) and isinstance(b1,
                                                           (int, float)) \
                    and b0 > 0:
                line += f"  (busy delta {(b1 - b0) / b0:+.1%})"
        print(line)
    resolved = at.get("resolved")
    if isinstance(resolved, dict) and win and resolved != win:
        diff = {k for k in resolved
                if win.get(k) != resolved.get(k)}
        if diff:
            print(f"\n  NOTE: the run OVERRODE the winner on "
                  f"{sorted(diff)} (env beats the tuner by precedence)")
    return 0


def _tune_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py tune",
        description="Autotune report from a BENCH json (extra.autotune)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"tune: {e}", file=sys.stderr)
        return 1
    return print_tune(doc)


# ---------------------------------------------------------------------------
# comms: per-program collective tables from a BENCH json (extra.commscope)
# ---------------------------------------------------------------------------

def print_comms(doc: dict) -> int:
    """The "what collectives does my layout run" report: per compiled
    program, one row per (op kind, mesh axis) with count / payload /
    analytic ICI estimate, plus any resharding findings — the evidence
    behind the step budget's estimated `collective` component."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')}, batch "
          f"{extra.get('batch')}, {extra.get('dtype')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    cs = extra.get("commscope")
    if not isinstance(cs, dict):
        print("  no extra.commscope section (commscope was off — rerun "
              "without BENCH_COMMSCOPE=0, with a BENCH_MESH layout)")
        return 1
    peaks = cs.get("peaks") or {}
    print(f"  ICI peaks: {peaks.get('device_kind')} "
          f"(table row {peaks.get('table_row')})  "
          f"{_fmt_bytes(peaks.get('ici_bytes_per_s'))}/s  "
          f"(estimates are analytic ring lower bounds, not measurements)")
    step = cs.get("step")
    if isinstance(step, dict):
        est = step.get("est_ms")
        line = f"  steady train program: {step.get('program')}"
        if _is_numlike(est):
            line += (f"  {_fmt_bytes(step.get('bytes'))}/step  "
                     f"est {est:.4f} ms/step")
        print(line)
    progs = cs.get("programs") or []
    if not progs:
        print("  no programs captured")
        return 0
    for p in progs:
        mesh = p.get("mesh")
        mesh_s = "x".join(f"{k}{v}" for k, v in (mesh or {}).items()) \
            or "no mesh"
        t = p.get("totals") or {}
        flag = ""
        if p.get("resharding_collectives"):
            flag = (f"  !! {p['resharding_collectives']} RESHARDING "
                    f"collective(s)")
        print(f"\n  {p.get('name')}  (mode={p.get('mode')}, {mesh_s})  "
              f"{t.get('count', 0)} collectives, "
              f"{_fmt_bytes(t.get('bytes', 0))}, "
              f"est {t.get('est_ms', 0):.4f} ms{flag}")
        rows = p.get("collectives") or []
        if not rows and p.get("hlo_available") is False:
            print("      (optimized HLO unavailable — inventory unknown)")
        for c in rows:
            print(f"      {c.get('kind', '?'):<19} x{c.get('count', 0):<4} "
                  f"{_fmt_bytes(c.get('bytes', 0)):>12}  "
                  f"est {c.get('est_ms', 0):.4f} ms  "
                  f"axis {c.get('axis') or '?'}")
        for r in p.get("resharding") or []:
            print(f"      RESHARD {r.get('kind')} ({r.get('reason')}): "
                  f"result {r.get('result_shape')}  operands "
                  f"{r.get('operand_shapes')}")
    return 0


def _is_numlike(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _comms_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py comms",
        description="per-program collective tables from a BENCH json "
                    "(extra.commscope)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"comms: {e}", file=sys.stderr)
        return 1
    return print_comms(doc)


# ---------------------------------------------------------------------------
# device: measured device-timeline report from a BENCH json
# (extra.devicescope)
# ---------------------------------------------------------------------------

def print_device(doc: dict) -> int:
    """The "what did the chip actually do" report: measured busy
    fraction, top-K device ops joined to their roofline verdicts,
    measured collective lanes, the idle-gap taxonomy, and the
    analytic-vs-measured reconciliation — everything a devicescope
    capture window ingested (docs/devicescope.md)."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')}, batch "
          f"{extra.get('batch')}, {extra.get('dtype')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    ds = extra.get("devicescope")
    if not isinstance(ds, dict):
        print("  no extra.devicescope section (devicescope was off — "
              "rerun with BENCH_DEVICESCOPE=1)")
        return 1
    win = ds.get("window")
    if not isinstance(win, dict):
        print("  devicescope was armed but no capture window completed "
              "(profiler busy, or the run ended before the window)")
        return 1
    wall = win.get("wall_ms")
    wall_s = f"{wall:.1f} ms" if _is_numlike(wall) else str(wall)
    print(f"  window: {win.get('steps')} steps over {wall_s}  "
          f"(requested {win.get('requested_steps')}, "
          f"complete={win.get('complete')})")
    print(f"    artifact: {win.get('path')}")
    if ds.get("error"):
        print(f"    INGEST ERROR: {ds['error']}")
    bf = ds.get("busy_fraction")
    if bf is not None:
        bar = "#" * int(round(bf * 40))
        print(f"\n  device busy fraction: {bf:.1%}  {bar}")
    per = ds.get("per_step") or {}
    if per:
        print(f"    per step: busy {per.get('device_busy_ms')} ms  "
              f"collective {per.get('collective_ms')} ms  "
              f"idle {per.get('idle_ms')} ms  "
              f"(over {ds.get('device_events')} device events, "
              f"{len(ds.get('lanes') or [])} lanes)")
    tops = ds.get("top_ops") or []
    if tops:
        print(f"\n  top device ops ({len(tops)}):")
        width = max(len(t.get("op", "?")) for t in tops)
        for t in tops:
            prog = t.get("program") or t.get("module") or "?"
            verdict = f"  [{t['verdict']}]" if t.get("verdict") else ""
            print(f"    {t.get('op', '?'):<{width}}  "
                  f"{t.get('total_ms', 0):>10.3f} ms  "
                  f"x{t.get('count', 0):<5} "
                  f"{prog}{verdict}")
    colls = ds.get("collectives") or {}
    rows = colls.get("by_kind") or []
    if rows:
        print(f"\n  measured collectives (union "
              f"{colls.get('union_ms')} ms):")
        for r in rows:
            print(f"    {r.get('kind', '?'):<19} x{r.get('count', 0):<5} "
                  f"{r.get('total_ms', 0):>10.3f} ms  "
                  f"axis {r.get('axis') or '?'}")
    gaps = ds.get("gaps")
    if isinstance(gaps, dict):
        tax = gaps.get("taxonomy") or {}
        print(f"\n  idle gaps: {gaps.get('count')} gaps, "
              f"{gaps.get('total_ms')} ms total, "
              f"max {gaps.get('max_ms')} ms")
        hist = gaps.get("histogram_ms") or {}
        if hist:
            print("    duration histogram (ms): "
                  + "  ".join(f"<={k}: {v}" for k, v in hist.items()))
        idle = sum(v for v in tax.values()
                   if isinstance(v, (int, float))) or None
        for key, label in (("input_starved_ms", "input-starved"),
                           ("dispatch_serialized_ms",
                            "dispatch-serialized"),
                           ("host_gap_ms", "host-gap")):
            v = tax.get(key)
            if v is None:
                continue
            share = f"  {v / idle:>6.1%}" if idle else ""
            print(f"    {label:<20} {v:>10.3f} ms{share}")
    recon = ds.get("reconciliation")
    if isinstance(recon, dict):
        _print_reconciliation(recon)
    elif bf is not None:
        print("\n  no reconciliation block (the step budget settled "
              "without this window — was perfscope off?)")
    return 0


def _device_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py device",
        description="measured device-timeline report from a BENCH json "
                    "(extra.devicescope)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"device: {e}", file=sys.stderr)
        return 1
    return print_device(doc)


# ---------------------------------------------------------------------------
# mem: memory report from a BENCH json (extra.memscope)
# ---------------------------------------------------------------------------

_SPARK_LEVELS = ".:-=+*#%@"


def _sparkline(values) -> str:
    """ASCII sparkline over a small series (the watermark tail)."""
    vals = [float(v) for v in values
            if isinstance(v, (int, float)) and not isinstance(v, bool)]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_LEVELS[0] * len(vals)
    out = []
    for v in vals:
        i = int((v - lo) / (hi - lo) * (len(_SPARK_LEVELS) - 1))
        out.append(_SPARK_LEVELS[i])
    return "".join(out)


def print_mem(doc: dict) -> int:
    """The "where does the memory go" report: the static per-program
    footprint table joined to the roofline verdicts (the largest peak
    flagged << PEAK), the watermark ring's p50/p95/peak with a tail
    sparkline, the capacity/headroom verdict, the analytic-vs-measured
    reconciliation, and — when the run died — the OOM post-mortem
    (docs/memscope.md)."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')}, batch "
          f"{extra.get('batch')}, {extra.get('dtype')})")
    if doc.get("status") == "env_failure":
        print(f"  run failed (env_failure): {doc.get('error')}")
        return 1
    ms = extra.get("memscope")
    if not isinstance(ms, dict):
        print("  no extra.memscope section (memscope was off — rerun "
              "with BENCH_MEMSCOPE=1)")
        return 1
    progs = [p for p in (ms.get("programs") or []) if isinstance(p, dict)]
    if progs:
        peaks = [p.get("peak_bytes") for p in progs]
        maxpeak = max((p for p in peaks
                       if isinstance(p, (int, float))
                       and not isinstance(p, bool)), default=None)
        print(f"\n  static program footprints ({len(progs)}):")
        width = max(len(p.get("name") or "?") for p in progs)
        for p in progs:
            name = p.get("name") or "?"
            if not p.get("available"):
                print(f"    {name:<{width}}  (no memory_analysis on "
                      f"this backend)")
                continue
            verdict = f"  [{p['roofline']}]" if p.get("roofline") else ""
            mark = "  << PEAK" if maxpeak is not None \
                and p.get("peak_bytes") == maxpeak else ""
            print(f"    {name:<{width}}  peak {_fmt_bytes(p.get('peak_bytes')):>11}  "
                  f"(args {_fmt_bytes(p.get('argument_bytes'))}, "
                  f"out {_fmt_bytes(p.get('output_bytes'))}, "
                  f"temp {_fmt_bytes(p.get('temp_bytes'))}, "
                  f"{p.get('provenance')})"
                  f"{verdict}{mark}")
    else:
        print("\n  no static footprints captured (no compile crossed "
              "the perfscope funnel while armed)")
    wm = ms.get("watermarks")
    if isinstance(wm, dict):
        print(f"\n  watermark ring: {wm.get('ring')}/"
              f"{wm.get('ring_limit')} samples held "
              f"({wm.get('samples')} taken)")
        for sect, label in (("device", "device bytes_in_use"),
                            ("host_rss", "host RSS")):
            blk = wm.get(sect)
            if not isinstance(blk, dict):
                if sect == "device":
                    print("    device allocator: unavailable on this "
                          "backend (host RSS carries the watermark)")
                continue
            print(f"    {label}: p50 {_fmt_bytes(blk.get('p50'))}  "
                  f"p95 {_fmt_bytes(blk.get('p95'))}  "
                  f"peak {_fmt_bytes(blk.get('peak'))}  "
                  f"latest {_fmt_bytes(blk.get('latest'))}")
        tail = wm.get("tail") or []
        key = "host_rss_bytes"
        series = [t.get(key) for t in tail if isinstance(t, dict)]
        spark = _sparkline(series)
        if spark:
            print(f"    tail ({len(spark)} samples, host RSS): "
                  f"[{spark}]")
    hr = ms.get("headroom")
    if isinstance(hr, dict):
        cap, frac = hr.get("capacity_bytes"), hr.get("headroom_fraction")
        verdict = hr.get("verdict")
        decor = {"ok": "OK", "tight": "!! TIGHT"}.get(verdict, verdict)
        line = (f"\n  headroom: {decor}")
        if frac is not None:
            line += f"  {frac:.1%} of capacity free"
        if cap:
            line += (f"  (in use {_fmt_bytes(hr.get('in_use_bytes'))} "
                     f"of {_fmt_bytes(cap)} "
                     f"[{hr.get('capacity_source')}], target "
                     f"{hr.get('target')})")
        print(line)
        if verdict == "tight":
            print("    predicted peaks above capacity x target are "
                  "infeasible — the autotuner prunes such candidates "
                  "pre-trial (reason=memory)")
    recon = ms.get("reconciliation")
    if isinstance(recon, dict) and recon.get("analytic"):
        a, m = recon["analytic"], recon.get("measured") or {}
        print(f"\n  reconciliation ({a.get('source')}):")
        print(f"    analytic per-device: "
              f"{_fmt_bytes(a.get('total_per_device'))} "
              f"(params {_fmt_bytes(a.get('param_bytes_per_device'))}, "
              f"states {_fmt_bytes(a.get('state_bytes_per_device'))}, "
              f"claimed reduction x{a.get('reduction')})")
        print(f"    measured: {_fmt_bytes(m.get('peak_bytes_in_use'))} "
              f"({m.get('source')})")
        drift = (recon.get("drift") or {}).get("per_device_bytes")
        if drift is not None:
            flag = "  !! STALE ESTIMATE" if recon.get("drift_warning") \
                else ""
            print(f"    drift: {drift:.1%} "
                  f"(threshold {recon.get('threshold'):.0%}){flag}")
    oom = ms.get("oom")
    if isinstance(oom, dict):
        print(f"\n  OOM POST-MORTEM (step {oom.get('step')}, program "
              f"{oom.get('program')!r}):")
        print(f"    error: {str(oom.get('error'))[:160]}")
        fp = oom.get("footprint")
        if isinstance(fp, dict) and fp.get("available"):
            print(f"    offending program's static peak: "
                  f"{_fmt_bytes(fp.get('peak_bytes'))} "
                  f"({fp.get('provenance')})")
        tail = oom.get("watermark_tail") or []
        series = [t.get("host_rss_bytes") for t in tail
                  if isinstance(t, dict)]
        spark = _sparkline(series)
        if spark:
            print(f"    memory in the steps before death: [{spark}]")
        bufs = oom.get("top_buffers") or []
        if bufs:
            print("    top live buffers at death:")
            for b in bufs[:8]:
                if isinstance(b, dict):
                    print(f"      {b.get('block', '?'):<28} "
                          f"{_fmt_bytes(b.get('bytes', 0)):>12}")
        knobs = oom.get("knobs")
        if isinstance(knobs, dict):
            set_knobs = {k: v for k, v in knobs.items() if v is not None}
            print(f"    resolved knobs: {set_knobs or '(all defaults)'}")
    elif ms.get("oom") is None:
        print("\n  no OOM recorded (good)")
    return 0


def _mem_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py mem",
        description="memory report from a BENCH json (extra.memscope)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"mem: {e}", file=sys.stderr)
        return 1
    return print_mem(doc)


# ---------------------------------------------------------------------------
# io: ingest-pipeline report from a BENCH json (extra.io +
# extra.devicescope's input_starved_split)
# ---------------------------------------------------------------------------

def print_io(doc: dict) -> int:
    """The "is the chip input-starved, and whose fault is it" report:
    the ingest pipeline's geometry and cumulative per-stage walls from
    extra.io, joined to devicescope's measured starvation split —
    ending in the one-line advice ("starved 31% of idle: 80% decode →
    raise io_workers, not prefetch depth")."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    io = extra.get("io")
    if not isinstance(io, dict):
        print("\n  no extra.io section (the run had no ingest pipeline "
              "— synthetic single-step mode, or a pre-PR-17 artifact)")
        return 1
    print(f"\n  pipeline: {io.get('workers')} decode worker(s), "
          f"depth {io.get('depth')}, "
          f"{io.get('batches_prefetched')} batches staged"
          + (f", {io.get('batches_skipped')} skipped (resume cursor)"
             if io.get("batches_skipped") else "")
          + (f", {io.get('records_read')} records read"
             if io.get("records_read") else "")
          + (f", injected slow-decode {io.get('slow_ms')} ms/batch"
             if io.get("slow_ms") else ""))
    stages = [("read (source next)", io.get("read_ms")),
              ("decode pool", io.get("decode_ms")),
              ("stage (reorder wait)", io.get("stage_ms")),
              ("put (host->device)", io.get("put_ms"))]
    total = sum(v for _, v in stages if isinstance(v, (int, float)))
    print("  cumulative stage walls (threads overlap — attribution, "
          "not a span):")
    for name, v in stages:
        v = float(v or 0.0)
        share = v / total if total else 0.0
        bar = "#" * int(round(share * 30))
        print(f"    {name:<22} {v:>10.1f} ms  {share:>6.1%}  {bar}")
    print(f"  consumer wait (io.wait_ms): {float(io.get('wait_ms') or 0):.1f} ms "
          f"— time next() sat on an empty buffer")
    ds = extra.get("devicescope") or {}
    gaps = ds.get("gaps") or {}
    starved = (gaps.get("taxonomy") or {}).get("input_starved_ms")
    split = gaps.get("input_starved_split")
    if not isinstance(split, dict):
        if starved in (None, 0):
            print("\n  device window: no input starvation measured — "
                  "the buffer kept ahead of the chip")
        else:
            print(f"\n  device window: input_starved {starved} ms, but "
                  f"no stage split (no stage walls in the window)")
        return 0
    idle = ds.get("idle_ms") or 0
    dom = split.get("dominant")
    parts = {"read": split.get("read_ms"),
             "decode": split.get("decode_ms"),
             "transfer": split.get("transfer_ms")}
    tot = sum(float(v or 0) for v in parts.values())
    dom_share = (float(parts.get(dom) or 0) / tot) if tot else 0.0
    starved_share = (float(starved or 0) / float(idle)) if idle else 0.0
    print(f"\n  device window: input_starved {starved} ms of "
          f"{idle} ms idle — split:")
    for k, v in parts.items():
        v = float(v or 0)
        share = v / tot if tot else 0.0
        tag = "  << DOMINANT" if k == dom else ""
        print(f"    {k:<10} {v:>9.1f} ms  {share:>6.1%}{tag}")
    knob = {"read": "shard wider / faster storage, not prefetch depth",
            "decode": "raise io_workers, not prefetch depth",
            "transfer": "raise prefetch_depth (deeper overlap), "
                        "not io_workers"}.get(dom, "")
    if knob:
        print(f"\n  ADVICE: starved {starved_share:.0%} of idle: "
              f"{dom_share:.0%} {dom} -> {knob}")
    return 0


def _io_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py io",
        description="ingest-pipeline report from a BENCH json "
                    "(extra.io + devicescope starvation split)")
    ap.add_argument("path", help="BENCH json (bench.py output or the "
                                 "driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"io: {e}", file=sys.stderr)
        return 1
    return print_io(doc)


# ---------------------------------------------------------------------------
# serve: tail-latency attribution report from a BENCH json
# (extra.servescope / extra.serve_load / extra.serving)
# ---------------------------------------------------------------------------

def _print_attr_group(grp: dict, indent: str = "    ") -> None:
    """One attribution group (overall or a bucket): the p99 cohort's
    component split with share bars, plus the independent component
    p99s underneath."""
    att = (grp.get("attribution") or {}).get("p99")
    e2e = grp.get("e2e_ms") or {}
    if not att:
        print(f"{indent}(no attribution — too few traced requests)")
        return
    print(f"{indent}e2e p50/p95/p99: {e2e.get('p50')}/{e2e.get('p95')}/"
          f"{e2e.get('p99')} ms  ({grp.get('count')} traced)")
    total = att.get("sum_ms") or 0.0
    print(f"{indent}p99 cohort ({att.get('cohort')} request(s) at "
          f"{att.get('e2e_ms')} ms):")
    for key, v in (att.get("components") or {}).items():
        share = v / total if total else 0.0
        bar = "#" * int(round(share * 30))
        tag = "  << TAIL" if key == att.get("top_component") else ""
        print(f"{indent}  {key.replace('_ms', ''):<15} {v:>9.3f} ms  "
              f"{share:>6.1%}  {bar}{tag}")


def print_serve(doc: dict) -> int:
    """The "why is my p99 what it is" report: the serve_load sweep
    table with its saturation knee, and servescope's per-bucket
    tail-latency attribution with roofline + resharding verdicts —
    ending in the one-line advice ("p99 is 83% queue_wait at bucket
    128 - raise max_batch, not the kernel")."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    sl = extra.get("serve_load")
    if isinstance(sl, dict) and sl.get("levels"):
        print(f"\n  ramp sweep ({len(sl['levels'])} levels, knee: "
              f"{sl.get('knee_reason')}):")
        for i, lv in enumerate(sl["levels"]):
            knee = "  << KNEE" if i == sl.get("knee_index") else ""
            print(f"    {lv.get('concurrency'):>5} clients  "
                  f"{lv.get('qps'):>9.1f} qps  p50/p95/p99 "
                  f"{lv.get('p50_ms')}/{lv.get('p95_ms')}/"
                  f"{lv.get('p99_ms')} ms  errors "
                  f"{lv.get('errors', 0)}{knee}")
    sv = extra.get("serving")
    if isinstance(sv, dict):
        print(f"\n  serving totals: {sv.get('responses')}/"
              f"{sv.get('requests')} responded over "
              f"{sv.get('batches')} batches (fill "
              f"{sv.get('batch_fill')}x); rejects: queue_full "
              f"{sv.get('rejected_queue_full', 0)}, deadline "
              f"{sv.get('rejected_deadline', 0)} (+"
              f"{sv.get('rejected_deadline_post_batch', 0)} post-batch), "
              f"invalid {sv.get('rejected_invalid', 0)}")
    ss = extra.get("servescope")
    if not isinstance(ss, dict):
        print("\n  no extra.servescope section (servescope was off — "
              "rerun without BENCH_SERVESCOPE=0)")
        return 1
    src = ss.get("device_exec_source")
    tag = ""
    if src == "measured(profile)":
        w = ss.get("device_window") or {}
        tag = (f"  [device_exec measured: devicescope window over "
               f"{w.get('dispatches')} dispatches"
               + (", DRIFT vs host wall" if w.get("drift_warning")
                  else "") + "]")
    elif src == "host_wall":
        tag = "  [device_exec: host wall around the executable]"
    print(f"\n  tail-latency attribution (sampled 1/"
          f"{ss.get('sample_every', 1)}, {ss.get('requests')} traced)"
          f"{tag}")
    print("\n  overall:")
    _print_attr_group(ss.get("overall") or {})
    for key, grp in sorted((ss.get("per_bucket") or {}).items(),
                           key=lambda kv: int(kv[0])
                           if kv[0].isdigit() else 0):
        verdict = grp.get("verdict")
        reshard = grp.get("resharding_collectives")
        flags = []
        if verdict:
            flags.append(verdict)
        if reshard:
            flags.append(f"!! {reshard} RESHARDING collective(s)")
        elif reshard == 0:
            flags.append("resharding-clean")
        fill = grp.get("fill")
        fill_s = f", fill {fill:.0%}" if isinstance(fill, float) else ""
        print(f"\n  bucket {key} ({', '.join(flags) or 'no verdicts'}"
              f"{fill_s}):")
        _print_attr_group(grp)
    advice = ss.get("advice")
    if advice:
        print(f"\n  ADVICE: {advice}")
    return 0


def _serve_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py serve",
        description="tail-latency attribution report from a BENCH json "
                    "(extra.servescope / extra.serve_load)")
    ap.add_argument("path", help="BENCH json (bench.py / serve_load.py "
                                 "output or the driver wrapper)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"serve: {e}", file=sys.stderr)
        return 1
    return print_serve(doc)


def print_fleet(doc: dict, events_path=None) -> int:
    """The fleet report from a serve_load ``--fleet`` BENCH json
    (`extra.fleet`): the per-replica dispatch table with
    client-observed tails, the imbalance ratio, the shared
    compile-cache verdict (did replica N+1's warmup hit?), and — when
    the events log is reachable — the drain/swap/readmit deploy
    timeline."""
    extra = doc.get("extra") or {}
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit')}  (model {extra.get('model')})")
    if doc.get("status") == "env_failure" or doc.get("error"):
        print(f"  run failed ({doc.get('status') or 'error'}): "
              f"{doc.get('error')}")
        return 1
    fl = extra.get("fleet")
    if not isinstance(fl, dict):
        print("\n  no extra.fleet section — this BENCH json is not a "
              "serve_load --fleet run (try `mxdiag.py serve` instead)")
        return 1
    print(f"\n  fleet: {fl.get('replicas')} replicas "
          f"({fl.get('batcher')} batcher), dispatch imbalance "
          f"{fl.get('dispatch_imbalance')} (max/mean; 1.0 = perfectly "
          f"balanced)")
    print(f"  router: {fl.get('routed')} routed, "
          f"{fl.get('routed_errors', 0)} forward errors, "
          f"{fl.get('no_replica_available', 0)} x no-replica-available")
    rows = fl.get("per_replica") or []
    if rows:
        print("\n  replica        requests  dispatched        qps  "
              "p50/p95/p99 ms")
        for row in rows:
            pcts = "/".join(str(row.get(k, "-"))
                            for k in ("p50_ms", "p95_ms", "p99_ms"))
            print(f"    {row.get('name', '?'):<12} {row.get('requests', 0):>9}"
                  f"  {row.get('dispatched', 0):>10}  {row.get('qps', 0):>9}"
                  f"  {pcts}")
    cache = fl.get("compile_cache")
    if isinstance(cache, dict):
        hits = cache.get("hits", 0)
        misses = cache.get("misses", 0)
        verdict = ("replica warmups were cache hits (no duplicate XLA "
                   "compiles)" if hits else
                   "NO cache hits — every replica recompiled from "
                   "scratch (cold or unshared cache dir?)")
        print(f"\n  shared AOT cache ({fl.get('cache_dir')}): "
              f"{hits} hits / {misses} misses / "
              f"{cache.get('stores', 0)} stores — {verdict}")
    # deploy timeline: fleet.drain / fleet.swap / fleet.readmit events
    path = events_path or extra.get("events_file")
    deploys = []
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                for ln in f:
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("kind") == "fleet":
                        deploys.append(rec)
        except OSError:
            pass
    if deploys:
        t0 = deploys[0].get("ts") or 0
        print(f"\n  deploy timeline ({len(deploys)} fleet events):")
        for rec in deploys:
            args = rec.get("args") or {}
            dt = (rec.get("ts") or 0) - t0
            detail = ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
            print(f"    +{dt:8.3f}s  {rec.get('name'):<14} {detail}")
    elif path:
        print(f"\n  no fleet drain/swap/readmit events in {path} "
              f"(no deploy happened during this run)")
    return 0


def _fleet_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py fleet",
        description="replica-fleet report from a serve_load --fleet "
                    "BENCH json (extra.fleet)")
    ap.add_argument("path", help="BENCH json (serve_load.py --fleet "
                                 "output or the driver wrapper)")
    ap.add_argument("--events", default=None,
                    help="mxtpu.events/1 log for the deploy timeline "
                         "(default: the json's extra.events_file)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 1
    return print_fleet(doc, events_path=args.events)


# ---------------------------------------------------------------------------
# merge: cross-rank timeline from per-rank flight dumps / event logs
# ---------------------------------------------------------------------------

def _load_timeline(path: str, fallback_rank: int):
    """Normalize one artifact into (rank, run_id, [records]); records are
    {ts, rank, step, kind, name, args?, src}. Event logs carry their own
    rank/run_id per record; flight dumps are tagged from their env
    snapshot (rank recorded at enable time) or, failing that, the
    file's position on the command line."""
    records = []
    if path.endswith(".jsonl"):
        rank, run_id = fallback_rank, None
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                if not str(rec.get("schema", "")).startswith(
                        "mxtpu.events/"):
                    raise ValueError(
                        f"{path}: not an mxtpu.events/ log (merge takes "
                        f"event logs and flight dumps, not metrics "
                        f"series)")
                rank = rec.get("rank", fallback_rank)
                run_id = rec.get("run_id", run_id)
                records.append({
                    "ts": rec["ts"], "rank": rank,
                    "run_id": rec.get("run_id"),
                    "step": rec.get("step"), "kind": rec.get("kind", "?"),
                    "name": rec.get("name", "?"),
                    "args": rec.get("args"), "src": path,
                    "mono": rec.get("mono")})
        return rank, run_id, records
    with open(path) as f:
        doc = json.load(f)
    if not (isinstance(doc, dict) and str(doc.get("schema", "")).startswith(
            "mxtpu.flight/")):
        raise ValueError(f"{path}: neither an event log nor a flight dump")
    env = doc.get("env") or {}
    rank = env.get("rank", fallback_rank)
    for ev in doc.get("events") or []:
        records.append({"ts": ev.get("ts", 0), "rank": rank, "step": None,
                        "kind": ev.get("kind", "?"),
                        "name": ev.get("name", "?"),
                        "args": ev.get("args"), "src": path,
                        "mono": ev.get("mono")})
    return rank, None, records


def merge_timelines(paths, out_path=None):
    """Merge-sort the artifacts by timestamp; returns the merged record
    list (and optionally writes it as mxtpu.events/1 JSONL)."""
    merged = []
    run_ids = set()
    for i, p in enumerate(paths):
        _, rid, recs = _load_timeline(p, fallback_rank=i)
        if rid:
            run_ids.add(rid)
        merged.extend(recs)
    merged.sort(key=lambda r: r["ts"])
    if len(run_ids) > 1:
        print(f"merge: WARNING: inputs span {len(run_ids)} run_ids "
              f"({sorted(run_ids)[:3]}...) — these are different runs",
              file=sys.stderr)
    # run_id for records that carry none (flight dumps): the inputs'
    # consensus when they agree, else an explicit unknown — NEVER a
    # run_id borrowed from an unrelated file (the correlation id must
    # stay honest in the validated merged output)
    fallback_rid = next(iter(run_ids)) if len(run_ids) == 1 else "unknown"
    if out_path:
        with open(out_path, "w") as f:
            last_ts = 0.0
            for r in merged:
                ts = max(float(r["ts"]), last_ts)   # keep the schema's
                last_ts = ts                        # monotonic-ts contract
                rec = {"schema": "mxtpu.events/2", "ts": ts,
                       "run_id": r.get("run_id") or fallback_rid,
                       "rank": int(r["rank"]), "step": r["step"],
                       "kind": r["kind"], "name": r["name"]}
                if isinstance(r.get("mono"), (int, float)):
                    # mono is only meaningful WITHIN its source process;
                    # carried through so a re-merge can still use it
                    rec["mono"] = r["mono"]
                if r.get("args"):
                    rec["args"] = r["args"]
                f.write(json.dumps(rec) + "\n")
    return merged


def print_merged(merged, tail=0) -> None:
    ranks = sorted({r["rank"] for r in merged})
    if not merged:
        print("merge: no records")
        return
    t0, t_end = merged[0]["ts"], merged[-1]["ts"]
    print(f"merged timeline: {len(merged)} records from "
          f"{len(ranks)} rank(s) {ranks} over {t_end - t0:.3f}s "
          f"({_fmt_ts(t0)} .. {_fmt_ts(t_end)})")
    show = merged[-tail:] if tail else merged
    if tail and len(merged) > tail:
        print(f"  ... {len(merged) - tail} earlier records elided ...")
    for r in show:
        step = f" step={r['step']}" if r.get("step") is not None else ""
        args = f"  {json.dumps(r['args'])}" if r.get("args") else ""
        print(f"  {r['ts'] - t0:>9.3f}s  [rank {r['rank']}] "
              f"{r['kind']:<10} {r['name']}{step}{args}")


# event names that ARE faults (detection) vs recovery ACTIONS — the
# join mxdiag recover renders: healthmon detects, resilience acts
# (docs/observability.md's "who acts on which verdict" column)
_RECOVER_FAULTS = ("healthmon.nan_loss", "healthmon.nan_grad_norm",
                   "healthmon.stall", "healthmon.step_time_regression",
                   "resilience.corrupt_checkpoint",
                   "resilience.save_error", "resilience.escalation")
_RECOVER_ACTIONS = ("resilience.rollback", "resilience.resume",
                    "resilience.restart_requested",
                    "resilience.rank_departed", "resilience.rank_joined")


def print_recover(merged) -> int:
    """Render the recovery timeline from a merged (or single-rank)
    mxtpu.events/1 stream: fault detected → rollback/restart → steps
    replayed → converged, healthmon alerts joined to resilience actions
    by run_id/step."""
    if not merged:
        print("recover: no records")
        return 1
    t0 = merged[0]["ts"]
    faults = [r for r in merged if r["name"] in _RECOVER_FAULTS]
    actions = [r for r in merged if r["name"] in _RECOVER_ACTIONS]
    saves = [r for r in merged
             if r["name"] == "resilience.checkpoint_saved"]
    steps = [r["step"] for r in merged
             if r["kind"] == "trainer" and r.get("step") is not None]
    run_ids = sorted({r.get("run_id") for r in merged if r.get("run_id")})
    print(f"recovery timeline: run_id={run_ids or ['?']}  "
          f"{len(faults)} fault(s), {len(actions)} recovery action(s), "
          f"{len(saves)} checkpoint(s)")
    if not faults and not actions:
        print("  clean run: no faults detected, no recoveries "
              "(checkpoints below are pure insurance)")
    rows = sorted(faults + actions + saves, key=lambda r: r["ts"])
    for r in rows:
        a = r.get("args") or {}
        if r["name"] in _RECOVER_FAULTS:
            tag = "FAULT "
            detail = json.dumps(a) if a else ""
        elif r["name"] == "resilience.checkpoint_saved":
            tag = "ckpt  "
            detail = (f"step {r.get('step')} "
                      f"({a.get('save_ms', '?')} ms async)")
        else:
            tag = "ACTION"
            if r["name"] == "resilience.rollback":
                detail = (f"step {a.get('from_step')} -> "
                          f"{a.get('to_step')} "
                          f"({a.get('steps_lost')} step(s) replayed, "
                          f"attempt {a.get('attempt')}, "
                          f"reason={a.get('reason')})")
            elif r["name"] == "resilience.resume":
                detail = (f"restored step {a.get('restored_step')}, "
                          f"cursor {a.get('cursor')} (restart-from-"
                          f"last-good)")
            elif r["name"] == "resilience.rank_departed":
                detail = (f"departed={a.get('departed')} -> members "
                          f"{a.get('members')} (re-formed at smaller "
                          f"world)")
            elif r["name"] == "resilience.rank_joined":
                detail = (f"joined={a.get('joined') or [a.get('rank')]} "
                          f"-> members {a.get('members')}")
            else:
                detail = json.dumps(a) if a else ""
        step = f" step={r['step']}" if r.get("step") is not None else ""
        print(f"  {r['ts'] - t0:>9.3f}s  [rank {r['rank']}] {tag} "
              f"{r['name']}{step}  {detail}")
    # fault -> first following action join, restricted to action kinds
    # that plausibly ANSWER that fault class (an unrelated later
    # rank_joined must not mark an un-acted-on NaN as handled)
    fault_answers = {
        "healthmon.nan_loss": ("resilience.rollback", "resilience.resume"),
        "healthmon.nan_grad_norm": ("resilience.rollback",
                                    "resilience.resume"),
        "healthmon.stall": ("resilience.restart_requested",
                            "resilience.resume"),
        "resilience.corrupt_checkpoint": ("resilience.resume",
                                          "resilience.rollback"),
        # retries exhausted: only a later process-level resume counts
        "resilience.escalation": ("resilience.resume",),
    }
    unhandled = []
    for fz in faults:
        answers = fault_answers.get(fz["name"])
        nxt = next((az for az in actions if az["ts"] >= fz["ts"]
                    and (answers is None or az["name"] in answers)), None)
        # regressions are advisory, and a failed ASYNC save is tolerated
        # by design (degraded durability, training continues) — neither
        # demands a recovery action after it
        if nxt is None and fz["name"] not in (
                "healthmon.step_time_regression",
                "resilience.save_error"):
            unhandled.append(fz)
    last_action_ts = max((az["ts"] for az in actions), default=None)
    tail_steps = [s for r in merged
                  if r["kind"] == "trainer" and r.get("step") is not None
                  and (last_action_ts is None or r["ts"] > last_action_ts)
                  for s in [r["step"]]]
    lost = sum(int((r.get("args") or {}).get("steps_lost") or 0)
               for r in actions if r["name"] == "resilience.rollback")
    print(f"summary: rollbacks="
          f"{sum(r['name'] == 'resilience.rollback' for r in actions)} "
          f"resumes={sum(r['name'] == 'resilience.resume' for r in actions)} "
          f"departures="
          f"{sum(r['name'] == 'resilience.rank_departed' for r in actions)} "
          f"joins="
          f"{sum(r['name'] == 'resilience.rank_joined' for r in actions)} "
          f"steps_replayed={lost}")
    if steps:
        post = (f", {len(tail_steps)} step(s) after the last recovery"
                if last_action_ts is not None else "")
        print(f"  progress: trained to step {max(steps)}{post} — "
              f"the run OUTLIVED its faults" if actions else
              f"  progress: trained to step {max(steps)}")
    if unhandled:
        print(f"  << UNHANDLED: {len(unhandled)} fault(s) with no "
              f"recovery action after them: "
              f"{[r['name'] for r in unhandled][:4]}")
        return 1
    return 0


def _recover_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py recover",
        description="render the fault -> recovery timeline from "
                    "mxtpu.events/1 logs (per-rank or merged)")
    ap.add_argument("paths", nargs="+",
                    help="event-log .jsonl files (and/or flight dumps)")
    args = ap.parse_args(argv)
    try:
        merged = merge_timelines(args.paths)
    except (OSError, ValueError, KeyError) as e:
        print(f"recover: {e}", file=sys.stderr)
        return 1
    return print_recover(merged)


def _lint_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py lint",
        description="render the mxlint findings report (rule ids + "
                    "fix-it hints) for the repo or specific paths")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package, "
                         "tools/ and bench.py)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only these rule ids (repeatable)")
    args = ap.parse_args(argv)
    import importlib.util
    ml_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "mxlint.py")
    spec = importlib.util.spec_from_file_location("mxlint_cli", ml_path)
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    rules = None
    if args.rule:
        mxl = cli._load_mxlint()
        rules = [mxl.rules.rule_by_id(r) for r in args.rule]
    findings, root = cli.run_lint(args.paths or None, rules=rules)
    print("== mxlint findings ==")
    if not findings:
        print("  tree is clean (0 findings)")
        return 0
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        fs = by_rule[rule]
        print(f"  [{rule}]  {len(fs)} finding{'s' if len(fs) != 1 else ''}")
        for f in fs:
            rel = os.path.relpath(f.path, root)
            print(f"    {rel}:{f.line}: {f.message}")
        if fs[0].hint:
            print(f"    fix: {fs[0].hint}")
    print(f"  {len(findings)} total — suppress only with "
          f"'# mxlint: disable=<rule> -- <reason>' (docs/mxlint.md)")
    return 1


def _merge_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py merge",
        description="interleave per-rank flight dumps / event logs into "
                    "one cross-rank timeline")
    ap.add_argument("paths", nargs="+",
                    help="event-log .jsonl and/or flight-dump .json files")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the merged timeline as "
                         "mxtpu.events/1 JSONL")
    ap.add_argument("--tail", type=int, default=0,
                    help="print only the last N merged records")
    args = ap.parse_args(argv)
    try:
        merged = merge_timelines(args.paths, out_path=args.out)
    except (OSError, ValueError, KeyError) as e:
        print(f"merge: {e}", file=sys.stderr)
        return 1
    print_merged(merged, tail=args.tail)
    if args.out:
        print(f"merged timeline written: {args.out}")
    return 0


# ---------------------------------------------------------------------------
# trace / pod: the fleetscope cross-process views
# ---------------------------------------------------------------------------

_SPAN_COMPONENTS = ("queue_wait_ms", "coalesce_delay_ms",
                    "pad_overhead_ms", "device_exec_ms", "respond_ms")


def print_trace(trace_id: str, records) -> int:
    """Render ONE request's cross-process span tree from merged event
    records: the router's ``fleetscope.request`` hop over the replica's
    ``serving.request`` span, the wire gap between them explicit, and
    the ``serving.batch`` dispatch the request coalesced into."""
    def _args(r):
        return r.get("args") or {}

    routers = [r for r in records if r.get("name") == "fleetscope.request"
               and _args(r).get("trace_id") == trace_id]
    replicas = [r for r in records if r.get("name") == "serving.request"
                and _args(r).get("trace_id") == trace_id]
    batches = [r for r in records if r.get("name") == "serving.batch"
               and trace_id in (_args(r).get("traces") or [])]
    if not routers and not replicas:
        print(f"trace: no records carry trace_id {trace_id!r} "
              f"(is fleetscope armed on both sides?)", file=sys.stderr)
        return 1
    srcs = sorted({r.get("src", "?") for r in routers + replicas + batches})
    print(f"== trace {trace_id} ==")
    print(f"  {len(routers)} router + {len(replicas)} replica + "
          f"{len(batches)} batch record(s) across {len(srcs)} file(s)")
    rc = 0
    for rr in routers:
        a = _args(rr)
        fw = a.get("forward_ms")
        fw_s = f", forward {fw:.2f} ms" if isinstance(fw, (int, float)) \
            else ""
        print(f"  router span {a.get('span_id', '?')}  "
              f"replica={a.get('replica')}  status={a.get('status')}  "
              f"e2e {a.get('e2e_ms', 0.0):.2f} ms{fw_s}   "
              f"[{rr.get('src', '?')}]")
        # the replica-side child(ren) of THIS hop: parent == router span
        children = [pr for pr in replicas
                    if _args(pr).get("parent_id") == a.get("span_id")]
        orphans = [pr for pr in replicas if pr not in children]
        for pr in children:
            p = _args(pr)
            e2e = p.get("e2e_ms")
            if isinstance(fw, (int, float)) and isinstance(e2e,
                                                           (int, float)):
                print(f"    |- wire gap {fw - e2e:.2f} ms  (router "
                      f"forward - replica e2e: duration difference, "
                      f"clock-skew free)")
            comp = " | ".join(
                f"{k[:-3]} {p[k]:.2f}" for k in _SPAN_COMPONENTS
                if isinstance(p.get(k), (int, float)))
            e2e_s = f"e2e {e2e:.2f} ms" if isinstance(e2e, (int, float)) \
                else f"status={p.get('status')}"
            print(f"    `- replica span {p.get('span_id', '?')} "
                  f"(parent {p.get('parent_id', '?')})  "
                  f"bucket={p.get('bucket')} batch={p.get('batch_id')}  "
                  f"{e2e_s}   [{pr.get('src', '?')}]")
            if comp:
                print(f"         {comp}")
            for br in batches:
                b = _args(br)
                if b.get("batch_id") == p.get("batch_id"):
                    shared = len(b.get("traces") or []) - 1
                    print(f"         batch {b.get('batch_id')}: "
                          f"n={b.get('n')} bucket={b.get('bucket')} "
                          f"exec {b.get('exec_ms')} ms"
                          + (f", co-batched with {shared} other "
                             f"traced request(s)" if shared > 0 else ""))
        if not children and replicas:
            rc = 1
            print(f"    << BROKEN JOIN: {len(orphans)} replica record(s) "
                  f"with this trace_id but parent != router span "
                  f"{a.get('span_id')!r}")
        elif not children:
            print(f"    (no replica-side span arrived — an unjoined "
                  f"forward: replica not sampling, or its events log "
                  f"was not given here)")
    for pr in (replicas if not routers else []):
        p = _args(pr)
        print(f"  replica span {p.get('span_id', '?')} (parent "
              f"{p.get('parent_id', '?')})  e2e "
              f"{p.get('e2e_ms', 0.0):.2f} ms — no router record "
              f"(router events log not given here?)   "
              f"[{pr.get('src', '?')}]")
    return rc


def _trace_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py trace",
        description="one request's cross-process span tree, joined on "
                    "the fleetscope trace_id across event logs")
    ap.add_argument("trace_id", help="32-hex fleetscope trace id (from "
                                     "a reply's trace_id field or an "
                                     "events record)")
    ap.add_argument("paths", nargs="+",
                    help="event-log .jsonl files from BOTH sides "
                         "(router's and each replica's)")
    args = ap.parse_args(argv)
    try:
        merged = merge_timelines(args.paths)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace: {e}", file=sys.stderr)
        return 1
    return print_trace(args.trace_id.strip().lower(), merged)


# straggler flag threshold: a replica whose trace p99 exceeds this
# multiple of the fleet median gets flagged (report-only — the router's
# least-loaded score is the control loop, this is the explanation)
_POD_STRAGGLER_MULT = 1.5


def print_pod(doc) -> int:
    """Render the fleet-wide trace aggregate (``extra.fleetscope``) from
    a serve_load --fleet BENCH json: join accounting, wire-gap
    percentiles, per-replica table with straggler flags, and the
    collector's clock-offset estimates."""
    extra = doc.get("extra") or {}
    fs = extra.get("fleetscope")
    if not isinstance(fs, dict):
        print("pod: no extra.fleetscope section (serve_load runs with "
              "fleetscope armed; --fleet N adds the per-replica rows)",
              file=sys.stderr)
        return 1
    print(f"== pod: cross-process trace aggregate "
          f"({extra.get('model', doc.get('metric', '?'))}) ==")
    rate = fs.get("join_rate")
    print(f"  traces: {fs.get('client_minted')} client-minted, "
          f"{fs.get('sampled')} sampled, {fs.get('joined')} joined "
          + (f"(join rate {rate:.1%})" if isinstance(rate, (int, float))
             else "") + f", {fs.get('unjoined_forwards')} unjoined "
          f"forward(s) — counted, never guessed")
    gap = fs.get("wire_gap_ms")
    if isinstance(gap, dict):
        print(f"  wire gap: p50 {gap.get('p50')} / p95 {gap.get('p95')} "
              f"/ p99 {gap.get('p99')} ms  (router forward - replica "
              f"e2e: clock-skew free)")
    rows = fs.get("per_replica") or []
    if rows:
        p99s = sorted(r["e2e_p99_ms"] for r in rows
                      if isinstance(r.get("e2e_p99_ms"), (int, float)))
        median = p99s[(len(p99s) - 1) // 2] if p99s else None
        print(f"  {'replica':<14} {'traces':>7} {'e2e p99 ms':>11} "
              f"{'wire gap p50':>13}")
        for r in rows:
            p99 = r.get("e2e_p99_ms")
            flag = ""
            if isinstance(p99, (int, float)) and median \
                    and p99 > _POD_STRAGGLER_MULT * median:
                flag = (f"   << straggler ({p99 / median:.2f}x the "
                        f"median p99; report-only)")
            p99_s = f"{p99:.3f}" if isinstance(p99, (int, float)) else "-"
            g = r.get("wire_gap_p50_ms")
            g_s = f"{g:.3f}" if isinstance(g, (int, float)) else "-"
            print(f"  {r.get('name', '?'):<14} {r.get('traces', 0):>7} "
                  f"{p99_s:>11} {g_s:>13}{flag}")
        spread = fs.get("replica_spread")
        if isinstance(spread, (int, float)):
            print(f"  replica spread (max/median p99): {spread:.2f}"
                  + ("  — balanced" if spread <= _POD_STRAGGLER_MULT
                     else "  — investigate the flagged replica"))
    coll = fs.get("collector")
    if isinstance(coll, dict):
        procs = coll.get("processes") or {}
        print(f"  collector: {len(procs)} process(es), "
              f"interval {coll.get('interval_s')} s")
        for name in sorted(procs):
            p = procs[name]
            off, bound = p.get("offset_s"), p.get("offset_bound_s")
            if isinstance(off, (int, float)):
                skew = (f"clock offset {off * 1e3:+.2f} ms "
                        f"+/- {bound * 1e3:.2f} ms"
                        if isinstance(bound, (int, float))
                        else f"clock offset {off * 1e3:+.2f} ms")
            else:
                skew = "no successful pull"
            err = f"  last_error={p.get('last_error')}" \
                if p.get("last_error") else ""
            print(f"    {name:<12} {p.get('pulls', 0):>3} pull(s)  "
                  f"{skew}{err}")
    return 0


def _pod_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="mxdiag.py pod",
        description="fleet-wide trace aggregate from a serve_load "
                    "--fleet BENCH json (extra.fleetscope)")
    ap.add_argument("path", help="BENCH json (serve_load.py output)")
    args = ap.parse_args(argv)
    try:
        doc = _load_bench(args.path)
    except (OSError, ValueError) as e:
        print(f"pod: {e}", file=sys.stderr)
        return 1
    return print_pod(doc)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "merge":
        return _merge_main(argv[1:])
    if argv and argv[0] == "perf":
        return _perf_main(argv[1:])
    if argv and argv[0] == "comms":
        return _comms_main(argv[1:])
    if argv and argv[0] == "device":
        return _device_main(argv[1:])
    if argv and argv[0] == "mem":
        return _mem_main(argv[1:])
    if argv and argv[0] == "io":
        return _io_main(argv[1:])
    if argv and argv[0] == "serve":
        return _serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        return _fleet_main(argv[1:])
    if argv and argv[0] == "trace":
        return _trace_main(argv[1:])
    if argv and argv[0] == "pod":
        return _pod_main(argv[1:])
    if argv and argv[0] == "tune":
        return _tune_main(argv[1:])
    if argv and argv[0] == "recover":
        return _recover_main(argv[1:])
    if argv and argv[0] == "lint":
        return _lint_main(argv[1:])
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="flight dump .json or metrics .jsonl")
    ap.add_argument("--events", type=int, default=40,
                    help="how many trailing ring events to print")
    args = ap.parse_args(argv)
    if args.path.endswith(".jsonl"):
        print_metrics(args.path)
        return 0
    try:
        with open(args.path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"{args.path}: {e}", file=sys.stderr)
        return 1
    if isinstance(doc, dict) and str(doc.get("schema", "")).startswith(
            "mxtpu.flight/"):
        print_flight(doc, args.events)
        return 0
    print(f"{args.path}: not a flight dump (schema="
          f"{doc.get('schema') if isinstance(doc, dict) else None!r}); "
          f"for Chrome traces use chrome://tracing", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
