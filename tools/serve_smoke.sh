#!/bin/bash
# Tier-1 serving smoke: freeze a model_zoo network ON CPU, start the
# ModelServer, fire 64 concurrent single-sample predicts through the
# dynamic batcher, and assert the acceptance contract end to end:
#   * zero dropped requests (responses == submitted, no rejects),
#   * batching demonstrably coalesced (batch-fill ratio > 1.5x),
#   * p99 latency recorded (and sane) in the BENCH json,
#   * outputs bit-exact vs direct eager net(x) on each served batch,
#   * serving counters + latency histograms present in the Prometheus
#     text / metrics JSONL exports and in the flight-recorder dump.
# bench.py itself hard-fails on drops/divergence; this script re-checks
# the emitted artifacts with tools/trace_check so a broken exporter
# can't pass silently. No TPU, no tunnel — safe anywhere, CI-cheap.
set -u
cd "$(dirname "$0")/.." || exit 1

DIAG_DIR=${MXTPU_DIAG_DIR:-/tmp/mxtpu_serve_smoke}
OUT=${1:-/tmp/mxtpu_serve_smoke_bench.json}
rm -rf "$DIAG_DIR"; mkdir -p "$DIAG_DIR"

echo "serve_smoke: 64 concurrent lenet predicts on CPU, diag armed"
JAX_PLATFORMS=cpu BENCH_MODEL=serving BENCH_SERVING_MODEL=lenet \
  BENCH_SERVING_CLIENTS=64 BENCH_SERVING_REQS=1 \
  BENCH_DIAG=1 BENCH_DIAG_INTERVAL_MS=100 \
  MXTPU_DIAG_DIR="$DIAG_DIR" \
  BENCH_TRACE_FILE="$DIAG_DIR/trace.json" \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$DIAG_DIR/bench.log"
rc=$?
if [ "$rc" != "0" ]; then
  echo "serve_smoke: bench.py failed rc=$rc"; tail -30 "$DIAG_DIR/bench.log"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
s = (doc.get("extra") or {}).get("serving") or {}
assert s, "no extra.serving section in BENCH json"
assert s["responses"] == s["requests"], \
    f"dropped requests: {s['requests'] - s['responses']}"
assert s.get("rejected_queue_full", 0) == 0 and \
    s.get("rejected_deadline", 0) == 0, f"rejections present: {s}"
assert s["batch_fill"] > 1.5, \
    f"batching did not coalesce: fill={s['batch_fill']}"
assert s["bit_exact"] is True, "serving outputs diverged from eager"
p99 = s["p99_ms"]
assert p99 and 0 < p99 < 30000, f"p99 insane: {p99}"
assert (s.get("latency_ms") or {}).get("count") == s["responses"], \
    "latency histogram lost observations"
print(f"serve_smoke: bench OK ({doc['value']} {doc['unit']}, "
      f"fill {s['batch_fill']}x over {s['batches']} batches, "
      f"p50/p95/p99 = {s['p50_ms']:.1f}/{s['p95_ms']:.1f}/"
      f"{p99:.1f} ms)")
EOF

# artifact validation: bench json (serving schema incl. histogram),
# chrome trace, flight dump, prometheus text, metrics jsonl
FLIGHT=$(python -c "import json,sys;print(json.load(open('$OUT'))['extra']['flight_file'])")
python tools/trace_check.py \
  "$OUT" "$DIAG_DIR/trace.json" "$FLIGHT" \
  "$DIAG_DIR/metrics.jsonl" "$DIAG_DIR/metrics.prom" || exit 1

# the serving traffic must be VISIBLE in the shared telemetry surfaces
python - "$FLIGHT" "$DIAG_DIR/metrics.prom" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
assert any(e["kind"] == "serving" for e in doc["events"]), \
    "no serving events in the flight dump"
assert doc["counter_kinds"].get("serving/serving.latency_ms") == \
    "histogram", "latency histogram missing from flight dump"
prom = open(sys.argv[2]).read()
assert "# TYPE serving_serving_latency_ms histogram" in prom, \
    "latency histogram missing from Prometheus export"
assert "serving_serving_responses" in prom, \
    "serving counters missing from Prometheus export"
print("serve_smoke: serving telemetry visible in flight + Prometheus")
EOF
echo "serve_smoke: all serving artifacts validate"
