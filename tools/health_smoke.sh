#!/bin/bash
# Tier-1 healthmon smoke — two parts, both CPU-only (no TPU, no tunnel):
#
#   1. tools/health_cluster.py — a REAL 2-process loopback cluster with
#      an injected slow rank (80 ms sleep on rank 1) and an injected NaN
#      loss (rank 0, step 7); asserts healthmon.collective_skew_ms
#      reports the skew with slowest-rank attribution on EVERY rank, the
#      NaN raises a watchdog alert (counter + flight event + structured
#      log record) within one step, and `mxdiag merge` interleaves the
#      per-rank events/flight artifacts into one validated cross-rank
#      timeline (tools/trace_check.py).
#
#   2. measured overhead — tools/health_overhead.py: 50 steps per side
#      of the CPU lenet bench step, healthmon off vs on at default
#      settings, INTERLEAVED in one process (paired-median verdict; two
#      sequential bench.py runs drift more than the effect). Budget:
#      < 5% (one retry absorbs scheduler noise on loaded CI).
#
#   3. pipeline validation — a short BENCH_HEALTHMON=1 bench.py run:
#      the BENCH json must carry the healthmon counters + events file,
#      and every artifact must pass tools/trace_check.py.
#
# Exit 0 iff all three hold.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT_DIR=${MXTPU_HM_OUT:-/tmp/mxtpu_health_smoke}
rm -rf "$OUT_DIR"; mkdir -p "$OUT_DIR"

echo "health_smoke: part 1 — 2-process cluster (slow rank + NaN)"
MXTPU_HM_OUT="$OUT_DIR/cluster" \
  timeout -k 10 600 python tools/health_cluster.py || {
  echo "health_smoke: cluster exercise FAILED"; exit 1; }

echo "health_smoke: part 2 — measured overhead (interleaved 50-step lenet)"
MXTPU_HM_OUT="$OUT_DIR/overhead" \
  timeout -k 10 900 python tools/health_overhead.py | tee "$OUT_DIR/overhead.json"
rc=${PIPESTATUS[0]}
if [ "$rc" = "3" ]; then
  echo "health_smoke: overhead over budget; one retry (noise check)"
  MXTPU_HM_OUT="$OUT_DIR/overhead" \
    timeout -k 10 900 python tools/health_overhead.py | tee "$OUT_DIR/overhead.json"
  rc=${PIPESTATUS[0]}
fi
[ "$rc" != "0" ] && { echo "health_smoke: overhead check FAILED (rc=$rc)"; exit 1; }

echo "health_smoke: part 3 — BENCH_HEALTHMON pipeline validation"
JAX_PLATFORMS=cpu BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=3 \
  BENCH_DTYPE=float32 BENCH_TRACE=0 BENCH_HEALTHMON=1 \
  MXTPU_DIAG_DIR="$OUT_DIR/bench_diag" \
  timeout -k 10 900 python bench.py > "$OUT_DIR/bench.json" \
  2> "$OUT_DIR/bench.log" || {
  echo "health_smoke: healthmon bench failed"
  tail -20 "$OUT_DIR/bench.log"; exit 1; }

python - "$OUT_DIR/bench.json" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
hm = (doc.get("extra") or {}).get("healthmon") or {}
assert hm.get("steps") == 3, f"healthmon saw {hm.get('steps')} steps"
assert hm.get("events_file"), "no events file in BENCH json"
assert hm["counters"].get("healthmon/healthmon.steps") == 3, \
    f"healthmon counters missing/wrong: {hm.get('counters')}"
print(f"health_smoke: bench OK ({doc['value']} {doc['unit']}, "
      f"{len(hm['counters'])} healthmon counters)")
EOF

# the healthmon bench's event log must validate as mxtpu.events/1
EVENTS=$(python -c "import json,sys;print(json.load(open('$OUT_DIR/bench.json'))['extra']['healthmon']['events_file'])")
python tools/trace_check.py "$EVENTS" "$OUT_DIR/bench.json" || exit 1
echo "health_smoke: all healthmon artifacts validate"
