#!/usr/bin/env python
"""Generate docs/API.md — the public API index, module by module.

Walks the installed package and lists every public callable/class with its
one-line docstring summary, so the surface can be audited against the
reference (python/mxnet/*) line by line without reading source. Re-run
after adding APIs:  JAX_PLATFORMS=cpu python tools/gen_api_doc.py
"""
import importlib
import inspect
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

MODULES = [
    ("incubator_mxnet_tpu", "top-level (mx.*)"),
    ("incubator_mxnet_tpu.ndarray", "mx.nd"),
    ("incubator_mxnet_tpu.ndarray.sparse", "mx.nd.sparse"),
    ("incubator_mxnet_tpu.ndarray.linalg", "mx.nd.linalg"),
    ("incubator_mxnet_tpu.ndarray.random", "mx.nd.random"),
    ("incubator_mxnet_tpu.symbol", "mx.sym"),
    ("incubator_mxnet_tpu.ops", "mx.nd (NN operator namespace)"),
    ("incubator_mxnet_tpu.autograd", "mx.autograd"),
    ("incubator_mxnet_tpu.gluon", "mx.gluon"),
    ("incubator_mxnet_tpu.gluon.nn", "mx.gluon.nn"),
    ("incubator_mxnet_tpu.gluon.rnn", "mx.gluon.rnn"),
    ("incubator_mxnet_tpu.gluon.loss", "mx.gluon.loss"),
    ("incubator_mxnet_tpu.gluon.data", "mx.gluon.data"),
    ("incubator_mxnet_tpu.gluon.contrib.nn", "mx.gluon.contrib.nn"),
    ("incubator_mxnet_tpu.gluon.contrib.rnn", "mx.gluon.contrib.rnn"),
    ("incubator_mxnet_tpu.gluon.symbolize", "gluon.symbolize (TPU-first)"),
    ("incubator_mxnet_tpu.gluon.contrib.estimator",
     "mx.gluon.contrib.estimator"),
    ("incubator_mxnet_tpu.optimizer", "mx.optimizer"),
    ("incubator_mxnet_tpu.optimizer.lr_scheduler", "mx.lr_scheduler"),
    ("incubator_mxnet_tpu.initializer", "mx.init"),
    ("incubator_mxnet_tpu.metric", "mx.metric"),
    ("incubator_mxnet_tpu.kvstore", "mx.kv"),
    ("incubator_mxnet_tpu.io", "mx.io"),
    ("incubator_mxnet_tpu.recordio", "mx.recordio"),
    ("incubator_mxnet_tpu.image", "mx.image"),
    ("incubator_mxnet_tpu.module", "mx.mod"),
    ("incubator_mxnet_tpu.models", "model zoo"),
    ("incubator_mxnet_tpu.rnn", "mx.rnn (symbol cells)"),
    ("incubator_mxnet_tpu.parallel", "parallel (TPU-first)"),
    ("incubator_mxnet_tpu.distributed", "mx.distributed"),
    ("incubator_mxnet_tpu.amp", "mx.amp"),
    ("incubator_mxnet_tpu.contrib.quantization", "contrib.quantization"),
    ("incubator_mxnet_tpu.contrib.onnx", "contrib.onnx"),
    ("incubator_mxnet_tpu.contrib.text", "contrib.text (vocab)"),
    ("incubator_mxnet_tpu.contrib.text.embedding",
     "contrib.text.embedding"),
    ("incubator_mxnet_tpu.callback", "mx.callback"),
    ("incubator_mxnet_tpu.monitor", "mx.monitor"),
    ("incubator_mxnet_tpu.visualization", "mx.viz"),
    ("incubator_mxnet_tpu.test_utils", "mx.test_utils"),
    ("incubator_mxnet_tpu.util", "mx.util"),
    ("incubator_mxnet_tpu.runtime", "native runtime bindings"),
    ("incubator_mxnet_tpu.profiler", "mx.profiler"),
]


def _summary(obj):
    doc = inspect.getdoc(obj) or ""
    line = doc.strip().splitlines()[0] if doc.strip() else ""
    if len(line) > 110:
        line = line[:110].rsplit(" ", 1)[0] + " …"
    if line.count("`") % 2:  # don't leave an unbalanced code span
        line = line.replace("`", "")
    return line.replace("|", "\\|")


def _public_names(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in dir(mod) if not n.startswith("_")]
    out = []
    for n in sorted(set(names)):
        try:
            obj = getattr(mod, n)
        except AttributeError:
            continue
        if inspect.ismodule(obj):
            continue
        if not (callable(obj) or inspect.isclass(obj)):
            continue
        # skip re-exports whose home module is a different top-level pkg
        home = getattr(obj, "__module__", "") or ""
        if home and not home.startswith("incubator_mxnet_tpu"):
            continue
        out.append((n, obj))
    return out


def main():
    header = [
        "# API index (auto-generated — tools/gen_api_doc.py)",
        "",
        "Every public class/function per module with its docstring's first",
        "line. Docstrings carry the reference-path citations",
        "(`python/mxnet/...`, `src/operator/...`); this file is the",
        "audit map of the surface itself.",
        "",
    ]
    lines = []
    total = 0
    for modname, label in MODULES:
        try:
            mod = importlib.import_module(modname)
        except Exception as e:  # noqa: BLE001
            lines += [f"## {label} — IMPORT FAILED: {e!r}", ""]
            continue
        names = _public_names(mod)
        total += len(names)
        lines += [f"## `{modname}` — {label} ({len(names)} public names)",
                  ""]
        lines.append("| name | kind | summary |")
        lines.append("|---|---|---|")
        for n, obj in names:
            kind = "class" if inspect.isclass(obj) else "fn"
            lines.append(f"| `{n}` | {kind} | {_summary(obj)} |")
        lines.append("")
    body = header + [f"**{total} public names across {len(MODULES)} "
                     "modules.**", ""] + lines
    out = os.path.join(ROOT, "docs", "API.md")
    with open(out, "w") as f:
        f.write("\n".join(body) + "\n")
    print(f"wrote {out}: {total} names")


if __name__ == "__main__":
    main()
