#!/bin/bash
# TPU relay health watcher (round 4). Probes the axon tunnel every 15 min
# with a tiny bf16 matmul + host fetch (a host fetch is the only real
# barrier through the relay). Appends one line per probe to the log.
# Never launches anything big: a wedged tunnel queues all clients behind
# the stuck compile, so the probe must stay tiny.
LOG=${1:-/root/repo/docs/bench_channel_r04.log}
while true; do
  ts=$(date -u +%H:%M)
  timeout 300 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
print(float((x @ x).sum()))
" >/dev/null 2>&1
  rc=$?
  echo "$ts rc=$rc" >> "$LOG"
  if [ "$rc" = "0" ]; then
    echo "$ts TUNNEL HEALTHY" >> "$LOG"
  fi
  sleep 900
done
