#!/bin/bash
# Tier-1 servescope smoke: the closed-loop load harness on CPU lenet
# (64 clients at the top of the ramp), asserting the acceptance
# contract end to end:
#   * tools/serve_load.py produces a trace_check-valid BENCH json with
#     a saturation knee and the full tail-latency attribution,
#   * the per-component p99 attribution sums to the measured e2e p99
#     within 15% (the acceptance bound; the spans' accounting identity
#     makes this structural),
#   * every compiled bucket carries its roofline verdict AND its
#     commscope resharding verdict (clean on an unsharded CPU model),
#   * the mxtpu.events/1 request/batch correlation stream validates,
#   * mxdiag.py serve renders the report,
#   * perf_regress.py accepts the artifact self-vs-self and FLAGS an
#     injected 20% p99 degradation at the serving threshold (0.15).
# No TPU, no tunnel - safe anywhere, CI-cheap.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_serve_load.json}
EVENTS="${OUT%.json}_events.jsonl"
LOG=${MXTPU_SERVESCOPE_SMOKE_LOG:-/tmp/mxtpu_servescope_smoke.log}

echo "servescope_smoke: ramped closed-loop sweep on CPU lenet (to 64 clients)"
JAX_PLATFORMS=cpu timeout -k 10 900 python tools/serve_load.py \
  --model lenet --ramp 4,8,16,32,64 --level-requests 96 \
  --out "$OUT" --events "$EVENTS" > "$LOG" 2>&1
rc=$?
if [ "$rc" != "0" ]; then
  echo "servescope_smoke: serve_load.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi
tail -5 "$LOG"

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc.get("status") != "env_failure", f"env failure: {doc.get('error')}"
extra = doc.get("extra") or {}
sl = extra.get("serve_load") or {}
assert sl.get("levels"), "no sweep levels in extra.serve_load"
assert isinstance(sl.get("knee_index"), int), "no saturation knee found"
ss = extra.get("servescope") or {}
assert ss, "no extra.servescope attribution in the BENCH json"
assert ss.get("requests") > 0, "servescope traced no requests"

# acceptance bound: the p99 attribution's component sum must sit within
# 15% of the measured e2e p99 it attributes — overall AND per bucket
def check(group, where):
    att = (group.get("attribution") or {}).get("p99")
    assert att, f"{where}: no p99 attribution"
    s, q = att["sum_ms"], att["e2e_ms"]
    comp_sum = sum(att["components"].values())
    assert abs(comp_sum - s) < max(0.05, 0.01 * s), \
        f"{where}: sum_ms {s} != component sum {comp_sum}"
    off = abs(s - q) / q if q else 0.0
    assert off <= 0.15, \
        f"{where}: p99 attribution {s:.3f} ms vs e2e p99 {q:.3f} ms " \
        f"({off:.1%} > 15%)"
    return off

offs = [check(ss["overall"], "overall")]
for b, grp in (ss.get("per_bucket") or {}).items():
    offs.append(check(grp, f"bucket {b}"))
    # every compiled bucket that served traffic carries BOTH verdicts
    assert grp.get("verdict") is not None, f"bucket {b}: no roofline verdict"
    assert grp.get("resharding_collectives") is not None, \
        f"bucket {b}: no resharding verdict"
    assert grp.get("resharding_collectives") == 0, \
        f"bucket {b}: unexpected resharding on an unsharded CPU model"
assert ss.get("advice"), "no attribution advice line"
knee = sl["levels"][sl["knee_index"]]
print(f"servescope_smoke: attribution OK (max quantile gap "
      f"{max(offs):.1%} <= 15%) over {ss['requests']} traced requests; "
      f"knee at {knee['concurrency']} clients, "
      f"{knee['qps']} qps, p99 {knee['p99_ms']} ms")
print(f"servescope_smoke: advice: {ss['advice']}")
EOF

# artifact validation: the BENCH json (servescope + serve_load schema)
# and the request/batch correlation event stream
python tools/trace_check.py "$OUT" "$EVENTS" || exit 1

# the correlation contract: every sampled serving.request joins a
# serving.batch record through batch_id
python - "$EVENTS" <<'EOF' || exit 1
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
reqs = [r for r in recs if r["name"] == "serving.request"]
batches = {(r.get("args") or {}).get("batch_id")
           for r in recs if r["name"] == "serving.batch"}
assert reqs, "no serving.request events emitted"
responded = [r for r in reqs if r["args"].get("status") == "responded"]
assert responded, "no responded serving.request events"
missing = [r for r in responded if r["args"].get("batch_id") not in batches]
assert not missing, \
    f"{len(missing)} request events with no matching serving.batch"
print(f"servescope_smoke: events OK ({len(responded)} request spans "
      f"joined to {len(batches)} batch records)")
EOF

# the report must render
python tools/mxdiag.py serve "$OUT" > /dev/null || {
  echo "servescope_smoke: mxdiag.py serve failed to render"; exit 1; }
echo "servescope_smoke: mxdiag serve renders"

# regression gate: self-vs-self must be clean; an injected 20% p99
# degradation must be FLAGGED at the serving threshold
BASE=/tmp/mxtpu_serve_load_base.json
BAD=/tmp/mxtpu_serve_load_bad.json
cp "$OUT" "$BASE"
python tools/perf_regress.py --p99-threshold 0.15 "$BASE" "$OUT" \
  > /dev/null || {
  echo "servescope_smoke: perf_regress flagged self-vs-self"; exit 1; }
python - "$OUT" "$BAD" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
sl = doc["extra"]["serve_load"]
k = sl["knee_index"]
sl["levels"][k]["p99_ms"] = round(sl["levels"][k]["p99_ms"] * 1.2, 3)
sl["p99_at_knee_ms"] = sl["levels"][k]["p99_ms"]
doc["extra"]["serving"]["p99_ms"] = sl["p99_at_knee_ms"]
json.dump(doc, open(sys.argv[2], "w"))
EOF
python tools/perf_regress.py --p99-threshold 0.15 "$BASE" "$BAD" \
  > /dev/null
if [ "$?" != "1" ]; then
  echo "servescope_smoke: injected 20% p99 degradation NOT flagged"
  exit 1
fi
echo "servescope_smoke: perf_regress clean self-vs-self, flags +20% p99"
echo "servescope_smoke: all servescope artifacts validate"
