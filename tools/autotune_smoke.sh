#!/bin/bash
# Tier-1 autotune smoke: CPU lenet through bench.py with MXTPU_AUTOTUNE=1
# against a FRESH tuning cache, twice, asserting the subsystem's core
# contracts from the emitted BENCH json:
#   run 1 (cache miss): a bounded search runs (trials >= 1, within the
#     budget), every scored trial carries measured(profile) provenance
#     (the devicescope window measured the busy fraction — not a host
#     guess), the winner's measured busy fraction >= the stepwise
#     default's (the baseline is a candidate, so the searched config can
#     never lose to it), pruning reasons are present, and the winner is
#     persisted;
#   run 2 (cache hit): cache_hit=true with trials=0 (zero search cost),
#     and the run actually STARTS tuned (the resolved knobs equal the
#     winner);
#   both runs: extra.autotune + the autotune.* counter family validate
#     under trace_check, `mxdiag.py tune` renders, and perf_regress
#     reports the two runs' knob configs as identical context.
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT1=${1:-/tmp/mxtpu_autotune_smoke_bench1.json}
OUT2=/tmp/mxtpu_autotune_smoke_bench2.json
LOG=/tmp/mxtpu_autotune_smoke.log
CACHE=/tmp/mxtpu_autotune_smoke_cache
DSDIR=/tmp/mxtpu_autotune_smoke_windows

rm -rf "$CACHE" "$DSDIR"
: > "$LOG"

run_bench() {
  JAX_PLATFORMS=cpu MXTPU_AUTOTUNE=1 MXTPU_AUTOTUNE_CACHE="$CACHE" \
    MXTPU_AUTOTUNE_BUDGET=3 MXTPU_AUTOTUNE_STEPS=8 \
    MXTPU_AUTOTUNE_TRIAL_TIMEOUT=420 \
    MXTPU_DEVICESCOPE_DIR="$DSDIR" \
    BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=24 \
    BENCH_DTYPE=float32 BENCH_K1_CONTROL=0 BENCH_PREFLIGHT=0 \
    BENCH_TRACE=0 BENCH_DEVICESCOPE=1 \
    timeout -k 10 1500 python bench.py > "$1" 2>> "$LOG"
}

echo "autotune_smoke: run 1 (fresh cache -> bounded search)"
run_bench "$OUT1"
rc=$?
if [ "$rc" != "0" ]; then
  echo "autotune_smoke: bench run 1 failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT1" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
at = (doc.get("extra") or {}).get("autotune")
assert isinstance(at, dict) and at.get("enabled") is True, \
    f"no enabled extra.autotune: {at!r}"
assert at.get("error") is None, f"autotune errored: {at.get('error')}"
assert at.get("cache_hit") is False, "run 1 must be a cache MISS"
assert 1 <= at.get("trials", 0) <= 3, \
    f"trials {at.get('trials')!r} outside the budget [1, 3]"
sc, df = at.get("score") or {}, at.get("default") or {}
assert sc.get("provenance") == "measured(profile)", \
    f"winner scored without a measured window: {sc!r}"
b1, b0 = sc.get("busy_fraction"), df.get("busy_fraction")
assert isinstance(b1, (int, float)) and isinstance(b0, (int, float)), \
    f"busy fractions missing: winner={b1!r} default={b0!r}"
assert b1 >= b0, \
    f"searched config's measured busy {b1} < stepwise default's {b0}"
assert at.get("winner"), "no winner config"
assert at.get("pruned"), "no pruning reasons recorded"
assert at.get("diagnosis") in ("input_starved", "dispatch_bound",
                               "device_bound", "unknown"), at.get("diagnosis")
c = (doc.get("extra") or {}).get("counters") or {}
for name in ("autotune/autotune.searches", "autotune/autotune.trials",
             "autotune/autotune.cache_misses"):
    assert name in c, f"counter {name} missing from BENCH json"
print(f"autotune_smoke: search OK (diagnosis={at['diagnosis']}, "
      f"{at['trials']} trials, busy {b0:.1%} -> {b1:.1%}, "
      f"winner {at['winner']})")
EOF

echo "autotune_smoke: run 2 (same key -> cache hit, 0 trials)"
run_bench "$OUT2"
rc=$?
if [ "$rc" != "0" ]; then
  echo "autotune_smoke: bench run 2 failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT1" "$OUT2" <<'EOF' || exit 1
import json, sys
d1 = json.load(open(sys.argv[1]))
d2 = json.load(open(sys.argv[2]))
at = (d2.get("extra") or {}).get("autotune")
assert isinstance(at, dict) and at.get("enabled") is True, at
assert at.get("cache_hit") is True, \
    f"run 2 must be a cache HIT, got {at.get('cache_hit')!r}"
assert at.get("trials") == 0, \
    f"cache hit must run 0 trials, got {at.get('trials')!r}"
win, resolved = at.get("winner") or {}, at.get("resolved") or {}
assert resolved == win, \
    f"run 2 did not START tuned: resolved {resolved} != winner {win}"
w1 = ((d1.get("extra") or {}).get("autotune") or {}).get("winner")
assert win == w1, f"cached winner drifted: {win} != {w1}"
print(f"autotune_smoke: cache hit OK (0 trials, started at {win})")
EOF

# schema-check both BENCH jsons (autotune section + counter families)
python tools/trace_check.py "$OUT1" "$OUT2" || exit 1

# the renderer must handle both shapes (search and cache-hit)
python tools/mxdiag.py tune "$OUT1" > /dev/null \
  || { echo "autotune_smoke: mxdiag tune failed on run 1"; exit 1; }
python tools/mxdiag.py tune "$OUT2" > /dev/null \
  || { echo "autotune_smoke: mxdiag tune failed on run 2"; exit 1; }

# perf_regress: the two runs ran the SAME tuned config — the knob
# context must say identical. Thresholds are opened wide: this step
# tests the knob-context plumbing, not throughput stability on a noisy
# 1-core CI box (the value/MFU gates have their own smoke).
REGOUT=$(python tools/perf_regress.py --threshold 0.9 \
           --busy-threshold 0.9 "$OUT1" "$OUT2") \
  || { echo "autotune_smoke: perf_regress failed on the tuned pair"; \
       echo "$REGOUT"; exit 1; }
echo "$REGOUT" | grep -q "knob config identical" \
  || { echo "autotune_smoke: knob-context note missing:"; \
       echo "$REGOUT"; exit 1; }

# and a knob DIFF must surface as context, never as a silent verdict:
# strip the tuning from a copy of run 2 so its resolved config reverts
# to the stepwise default, then expect the CONTEXT note naming the diff
python - "$OUT2" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
at = doc["extra"]["autotune"]
at["resolved"] = dict(at["resolved"], loop_chunk=0)
json.dump(doc, open("/tmp/mxtpu_autotune_smoke_diffknobs.json", "w"))
EOF
DIFFOUT=$(python tools/perf_regress.py --threshold 0.9 \
            --busy-threshold 0.9 "$OUT1" \
            /tmp/mxtpu_autotune_smoke_diffknobs.json)
echo "$DIFFOUT" | grep -q "CONTEXT: knob config differs" \
  || { echo "autotune_smoke: knob-diff context note missing:"; \
       echo "$DIFFOUT"; exit 1; }

echo "autotune_smoke: OK"
