#!/usr/bin/env python
"""Validate a Chrome trace-event JSON file (as written by
`incubator_mxnet_tpu.profiler.dump()` or any trace-event producer).

Checks the subset of the Trace Event Format that chrome://tracing /
Perfetto actually require to render:

* top level is either a JSON array of events or an object whose
  ``traceEvents`` is an array;
* every event is an object with a string ``name`` and a string ``ph``;
* complete events (``ph == "X"``) carry numeric, non-negative ``ts`` and
  ``dur``;
* instant/counter events (``ph in "iIC"``) carry a numeric ``ts``;
* ``pid``/``tid``, when present, are integers.

Usage:
    python tools/trace_check.py trace.json [more.json ...]

Exit status 0 iff every file validates; errors are printed one per line.
bench.py imports :func:`check_trace` and fails the run on a malformed
dump, so a broken profiler can't silently ship garbage traces.
"""
from __future__ import annotations

import json
import numbers
import sys

__all__ = ["check_trace", "check_events"]


def _is_num(x) -> bool:
    return isinstance(x, numbers.Real) and not isinstance(x, bool)


def check_events(events) -> list:
    """Validate a list of trace events. Returns a list of error strings
    (empty = valid)."""
    errors = []
    if not isinstance(events, list):
        return [f"traceEvents must be a list, got {type(events).__name__}"]
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: missing/empty 'name'")
        ph = ev.get("ph")
        if not isinstance(ph, str) or len(ph) != 1:
            errors.append(f"{where} ({name!r}): missing 'ph'")
            continue
        if ph == "X":
            if not _is_num(ev.get("ts")) or ev["ts"] < 0:
                errors.append(f"{where} ({name!r}): 'X' event needs numeric "
                              f"ts >= 0, got {ev.get('ts')!r}")
            if not _is_num(ev.get("dur")) or ev["dur"] < 0:
                errors.append(f"{where} ({name!r}): 'X' event needs numeric "
                              f"dur >= 0, got {ev.get('dur')!r}")
        elif ph in ("i", "I", "C", "B", "E"):
            if not _is_num(ev.get("ts")):
                errors.append(f"{where} ({name!r}): '{ph}' event needs "
                              f"numeric ts, got {ev.get('ts')!r}")
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                errors.append(f"{where} ({name!r}): '{key}' must be int, "
                              f"got {ev[key]!r}")
    return errors


def check_trace(path: str) -> list:
    """Validate one trace file. Returns a list of error strings."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable/invalid JSON: {e}"]
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict):
        if "traceEvents" not in doc:
            return [f"{path}: object form requires a 'traceEvents' key"]
        events = doc["traceEvents"]
    else:
        return [f"{path}: top level must be a list or object, "
                f"got {type(doc).__name__}"]
    return [f"{path}: {e}" for e in check_events(events)]


def main(argv) -> int:
    if not argv:
        print(__doc__.strip().splitlines()[0])
        print("usage: python tools/trace_check.py trace.json [...]")
        return 2
    rc = 0
    for path in argv:
        errors = check_trace(path)
        if errors:
            rc = 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            print(f"{path}: OK")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
