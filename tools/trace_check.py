#!/usr/bin/env python
"""Validate observability artifacts produced by this framework:

* **Chrome trace-event JSON** (`profiler.dump()`) — the subset of the
  Trace Event Format that chrome://tracing / Perfetto require to render;
* **flight-recorder dumps** (`diagnostics.flight`) — versioned schema
  (``mxtpu.flight/1``), required header fields, events with monotonic
  non-decreasing timestamps;
* **Prometheus text exposition** (`diagnostics.export.prometheus_text`)
  — metric-name/label/value syntax, `# TYPE` declarations;
* **metrics newline-JSON** (`diagnostics` sampler `metrics.jsonl`) —
  per-line schema, non-decreasing sample timestamps, and MONOTONIC
  counters: any metric declared `kind == "counter"` must never decrease
  across samples (a decrease means a broken registry or a torn read).
  Histogram-kind metrics are validated structurally (cumulative buckets,
  `+Inf` == count) and their observation count must be monotonic;
* **bench result JSON** (`BENCH_*.json`) — when the result carries an
  `extra.serving` section (the serving benchmark), its latency
  histograms, percentiles, and fill-ratio/error accounting are
  structurally validated;
* **structured event logs** (`healthmon.events` / ``mxtpu.events/1``
  JSONL, including `mxdiag merge` output) — per-record schema with the
  run_id/rank/step correlation ids, non-decreasing timestamps;
* **counter families** — any `healthmon/*`, `io/*`, `trainloop/*`,
  `perfscope/*`, `commscope/*`, `devicescope/*`, `servescope/*`,
  `autotune/*`, `mxlint/*` or
  `sharding/*` metric appearing in a flight dump or metrics series must
  belong to the known family table with the declared kind (an unknown
  or re-kinded metric means a producer drifted from the documented
  schema). The tables have ONE home —
  `incubator_mxnet_tpu/mxlint/families.py` — which this validator and
  mxlint's `unregistered-counter` rule both derive from.

Usage:
    python tools/trace_check.py FILE [more files ...]

File kind is auto-detected (extension, then content). Exit status 0 iff
every file validates; errors are printed one per line. bench.py imports
:func:`check_trace` / :func:`check_file` and fails the run on malformed
output, so a broken exporter can't silently ship garbage telemetry.
"""
from __future__ import annotations

import json
import numbers
import re
import sys

__all__ = ["check_trace", "check_events", "check_flight", "check_prom",
           "check_metrics_jsonl", "check_histogram_snapshot",
           "check_bench_json", "check_events_jsonl",
           "check_healthmon_kinds", "check_perfscope_extra",
           "check_commscope_extra", "check_devicescope_extra",
           "check_servescope_extra", "check_serve_load_extra",
           "check_sharding_extra", "check_resilience_extra",
           "check_autotune_extra", "check_mxlint_extra", "check_io_extra",
           "check_embedding_extra", "check_fleetscope_extra",
           "check_file"]

FLIGHT_SCHEMA_PREFIX = "mxtpu.flight/"
EVENTS_SCHEMA_PREFIX = "mxtpu.events/"

# The counter-family tables. ONE home: they derive from
# incubator_mxnet_tpu/mxlint/families.py (pure stdlib data, loaded by
# path so this validator needs no framework/jax import) — the same
# source mxlint's `unregistered-counter` rule reads, so the validator
# and the linter cannot disagree. Adding a metric to a governed family
# is one edit THERE; tests/test_mxlint.py fails on drift between these
# module globals and the home tables.
def _load_families():
    import importlib.util
    import os as _os
    here = _os.path.dirname(_os.path.abspath(__file__))
    path = _os.path.join(_os.path.dirname(here), "incubator_mxnet_tpu",
                         "mxlint", "families.py")
    spec = importlib.util.spec_from_file_location(
        "mxtpu_mxlint_families", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_families = (sys.modules.get("incubator_mxnet_tpu.mxlint.families")
             or _load_families())

HEALTHMON_FAMILIES = _families.family_table("healthmon")
# io.* (device prefetcher) + trainloop.* (whole-loop executor) share one
# exported table (docs/trainloop.md documents each metric)
IO_TRAINLOOP_FAMILIES = _families.family_table("io", "trainloop")
SHARDING_FAMILIES = _families.family_table("sharding")
PERFSCOPE_FAMILIES = _families.family_table("perfscope")
COMMSCOPE_FAMILIES = _families.family_table("commscope")
DEVICESCOPE_FAMILIES = _families.family_table("devicescope")
SERVESCOPE_FAMILIES = _families.family_table("servescope")
# memscope.* — static footprints + watermark ring + OOM forensics
# (docs/memscope.md)
MEMSCOPE_FAMILIES = _families.family_table("memscope")
RESILIENCE_FAMILIES = _families.family_table("resilience")
AUTOTUNE_FAMILIES = _families.family_table("autotune")
# mxlint.* — the strict-mode jit-program auditor (docs/mxlint.md)
MXLINT_FAMILIES = _families.family_table("mxlint")
# fleet.* — continuous batching + replica fleet (docs/serving.md)
FLEET_FAMILIES = _families.family_table("fleet")
# embedding.* — sharded tables, dedup lookup, row-sparse updates
# (docs/embedding.md)
EMBEDDING_FAMILIES = _families.family_table("embedding")
# fleetscope.* — cross-process trace context + clock-aligned collection
# (docs/fleetscope.md)
FLEETSCOPE_FAMILIES = _families.family_table("fleetscope")

# sharding modes a BENCH extra.sharding may declare (parallel/sharding.py)
SHARDING_MODES = ("dp", "fsdp", "auto")

ROOFLINE_VERDICTS = ("compute_bound", "hbm_bound", "trivial", "unknown")

# the closed collective op-kind taxonomy an `extra.commscope` record may
# use (commscope/hlo.py COLLECTIVE_KINDS — unknown HLO spellings are
# bucketed as "other" by the producer, never invented here)
COMMSCOPE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute", "other")

# provenance values the step budget's collective component may declare:
# kvstore-counter / devicescope-window measurements, commscope's static
# estimate, or an honest unknown
COLLECTIVE_SOURCES = ("measured", "measured(profile)", "estimated",
                      "unavailable")

# idle-gap taxonomy buckets an `extra.devicescope` gaps block classifies
DEVICESCOPE_GAP_TAXONOMY = ("input_starved_ms", "dispatch_serialized_ms",
                            "host_gap_ms")

# the closed footprint provenance taxonomy an `extra.memscope` program
# record may declare (memscope/footprint.py FOOTPRINT_PROVENANCE):
# XLA reported the peak, we derived it from the component sum, or the
# backend has no memory_analysis at all
MEMSCOPE_PROVENANCE = ("reported", "derived", "unavailable")

# capacity resolution sources (memscope.device_capacity)
MEMSCOPE_CAPACITY_SOURCES = ("env", "memory_stats", "host_ram", "unknown")

# headroom verdicts (memscope.headroom_state) and the in-use pairing
MEMSCOPE_HEADROOM_VERDICTS = ("ok", "tight", "unknown")
MEMSCOPE_IN_USE_SOURCES = ("memory_stats", "host_rss")

# non-negative byte fields of one footprint record (peak checked apart)
MEMSCOPE_BYTE_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                        "alias_bytes", "generated_code_bytes")

MEMSCOPE_OOM_SCHEMA = "mxtpu.memscope.oom/1"

# per-stage attribution keys of the optional input_starved_split block
# (devicescope/ingest.py _starved_split), plus its dominant-stage tags
DEVICESCOPE_STARVED_SPLIT = ("read_ms", "decode_ms", "transfer_ms")
DEVICESCOPE_STARVED_DOMINANTS = ("read", "decode", "transfer")

# score provenance an `extra.autotune` record may declare: the trial's
# busy fraction came from a measured devicescope window, or degraded to
# host-side wall/throughput scoring (autotune/trial.py SCORE_SOURCES)
AUTOTUNE_SCORE_SOURCES = ("measured(profile)", "host_wall")

# the knob fields a winner/resolved config may carry
# (autotune/knobs.py KNOB_FIELDS)
AUTOTUNE_KNOB_FIELDS = ("loop_chunk", "remat", "remat_policy",
                        "prefetch_depth", "io_workers", "pallas", "mesh",
                        "batch")

AUTOTUNE_PALLAS_MODES = ("auto", "on", "force", "off")
AUTOTUNE_REMAT_POLICIES = (None, "dots", "nothing", "everything")
AUTOTUNE_TRIAL_STATUSES = ("ok", "failed")
AUTOTUNE_DIAGNOSES = ("input_starved", "dispatch_bound", "device_bound",
                      "unknown", None)

# the closed request-latency component taxonomy an `extra.servescope`
# attribution decomposes into (servescope/spans.py COMPONENTS)
SERVESCOPE_COMPONENTS = ("queue_wait_ms", "coalesce_delay_ms",
                         "pad_overhead_ms", "device_exec_ms",
                         "respond_ms")

# provenance values the attribution's device_exec component may declare
SERVESCOPE_DEVICE_SOURCES = ("host_wall", "measured(profile)")

# structural tolerance on |cohort sum - e2e quantile| / quantile: the
# cohort-mean sum equals the cohort's mean e2e exactly, so this only
# bounds cohort tightness. The CPU smoke enforces the acceptance bound
# of 15%; the validator allows a little more slack (same split as
# PERFSCOPE_SUM_TOLERANCE).
SERVESCOPE_SUM_TOLERANCE = 0.25

# decomposition components that must sum (with "other" absorbing the
# residual) to the measured step time
PERFSCOPE_COMPONENTS = ("device_compute_ms", "collective_ms",
                        "input_wait_ms", "host_gap_ms", "other_ms")

# structural tolerance on |sum - step_ms| / step_ms. The CPU smoke
# enforces the acceptance bound of 15%; the validator allows a little
# more slack so a noisy-box artifact is flagged by the smoke (a perf
# verdict) rather than rejected as malformed telemetry.
PERFSCOPE_SUM_TOLERANCE = 0.25


def _is_num(x) -> bool:
    return isinstance(x, numbers.Real) and not isinstance(x, bool)


# ---------------------------------------------------------------------------
# Chrome trace events
# ---------------------------------------------------------------------------

def check_events(events) -> list:
    """Validate a list of trace events. Returns a list of error strings
    (empty = valid)."""
    errors = []
    if not isinstance(events, list):
        return [f"traceEvents must be a list, got {type(events).__name__}"]
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: missing/empty 'name'")
        ph = ev.get("ph")
        if not isinstance(ph, str) or len(ph) != 1:
            errors.append(f"{where} ({name!r}): missing 'ph'")
            continue
        if ph == "X":
            if not _is_num(ev.get("ts")) or ev["ts"] < 0:
                errors.append(f"{where} ({name!r}): 'X' event needs numeric "
                              f"ts >= 0, got {ev.get('ts')!r}")
            if not _is_num(ev.get("dur")) or ev["dur"] < 0:
                errors.append(f"{where} ({name!r}): 'X' event needs numeric "
                              f"dur >= 0, got {ev.get('dur')!r}")
        elif ph in ("i", "I", "C", "B", "E"):
            if not _is_num(ev.get("ts")):
                errors.append(f"{where} ({name!r}): '{ph}' event needs "
                              f"numeric ts, got {ev.get('ts')!r}")
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                errors.append(f"{where} ({name!r}): '{key}' must be int, "
                              f"got {ev[key]!r}")
    return errors


def check_trace(path: str) -> list:
    """Validate one Chrome trace file. Returns a list of error strings."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable/invalid JSON: {e}"]
    if isinstance(doc, list):
        events = doc
    elif isinstance(doc, dict):
        if "traceEvents" not in doc:
            return [f"{path}: object form requires a 'traceEvents' key"]
        events = doc["traceEvents"]
    else:
        return [f"{path}: top level must be a list or object, "
                f"got {type(doc).__name__}"]
    return [f"{path}: {e}" for e in check_events(events)]


# ---------------------------------------------------------------------------
# flight-recorder dumps
# ---------------------------------------------------------------------------

def check_flight(path: str) -> list:
    """Validate a diagnostics flight-recorder dump."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable/invalid JSON: {e}"]
    errors = []
    if not isinstance(doc, dict):
        return [f"{path}: flight dump must be a JSON object"]
    schema = doc.get("schema")
    if not isinstance(schema, str) or \
            not schema.startswith(FLIGHT_SCHEMA_PREFIX):
        errors.append(f"schema must start with {FLIGHT_SCHEMA_PREFIX!r}, "
                      f"got {schema!r}")
    for key, typ in (("dumped_at", numbers.Real), ("reason", str),
                     ("env", dict), ("config", dict), ("counters", dict),
                     ("counter_kinds", dict), ("events", list)):
        if not isinstance(doc.get(key), typ):
            errors.append(f"missing/mistyped {key!r} "
                          f"(want {typ.__name__}, "
                          f"got {type(doc.get(key)).__name__})")
    events = doc.get("events")
    if isinstance(events, list):
        last_ts = None
        for i, ev in enumerate(events):
            if not isinstance(ev, dict):
                errors.append(f"events[{i}]: not an object")
                continue
            if not _is_num(ev.get("ts")):
                errors.append(f"events[{i}]: needs numeric 'ts', "
                              f"got {ev.get('ts')!r}")
                continue
            for key in ("kind", "name"):
                if not isinstance(ev.get(key), str) or not ev[key]:
                    errors.append(f"events[{i}]: missing/empty {key!r}")
            if last_ts is not None and ev["ts"] < last_ts:
                errors.append(f"events[{i}]: ts went backwards "
                              f"({ev['ts']} < {last_ts})")
            last_ts = ev["ts"]
        n = doc.get("n_events")
        if isinstance(n, int) and n != len(events):
            errors.append(f"n_events={n} but {len(events)} events present")
    kinds = doc.get("counter_kinds")
    if isinstance(kinds, dict):
        bad = [k for k, v in kinds.items()
               if v not in ("counter", "gauge", "histogram")]
        if bad:
            errors.append(f"counter_kinds values must be "
                          f"counter|gauge|histogram: {bad[:3]}")
        counters = doc.get("counters")
        if isinstance(counters, dict):
            for k, kind in kinds.items():
                if kind == "histogram" and k in counters:
                    errors += [f"counters[{k!r}]: {e}" for e in
                               check_histogram_snapshot(counters[k])]
        errors += check_healthmon_kinds(kinds)
    return [f"{path}: {e}" for e in errors]


# ---------------------------------------------------------------------------
# healthmon counter families
# ---------------------------------------------------------------------------

def check_healthmon_kinds(kinds: dict) -> list:
    """Every healthmon/*, io/*, trainloop/*, perfscope/*, commscope/*,
    devicescope/*, servescope/* and sharding/* metric must belong to
    its family table with the declared kind."""
    errors = []
    tables = (("healthmon/", HEALTHMON_FAMILIES, "HEALTHMON_FAMILIES"),
              ("io/", IO_TRAINLOOP_FAMILIES, "IO_TRAINLOOP_FAMILIES"),
              ("trainloop/", IO_TRAINLOOP_FAMILIES,
               "IO_TRAINLOOP_FAMILIES"),
              ("perfscope/", PERFSCOPE_FAMILIES, "PERFSCOPE_FAMILIES"),
              ("commscope/", COMMSCOPE_FAMILIES, "COMMSCOPE_FAMILIES"),
              ("devicescope/", DEVICESCOPE_FAMILIES,
               "DEVICESCOPE_FAMILIES"),
              ("servescope/", SERVESCOPE_FAMILIES, "SERVESCOPE_FAMILIES"),
              ("memscope/", MEMSCOPE_FAMILIES, "MEMSCOPE_FAMILIES"),
              ("resilience/", RESILIENCE_FAMILIES,
               "RESILIENCE_FAMILIES"),
              ("autotune/", AUTOTUNE_FAMILIES, "AUTOTUNE_FAMILIES"),
              ("mxlint/", MXLINT_FAMILIES, "MXLINT_FAMILIES"),
              ("fleet/", FLEET_FAMILIES, "FLEET_FAMILIES"),
              ("fleetscope/", FLEETSCOPE_FAMILIES,
               "FLEETSCOPE_FAMILIES"),
              ("sharding/", SHARDING_FAMILIES, "SHARDING_FAMILIES"))
    for k, kind in sorted(kinds.items()):
        for prefix, table, tname in tables:
            if not k.startswith(prefix):
                continue
            want = table.get(k)
            if want is None:
                errors.append(f"unknown {prefix.rstrip('/')} counter "
                              f"family {k!r} (update {tname} if "
                              f"intentional)")
            elif kind != want:
                errors.append(f"counter {k!r} has kind {kind!r}, "
                              f"schema says {want!r}")
    return errors


# ---------------------------------------------------------------------------
# structured event logs (mxtpu.events/1 JSONL)
# ---------------------------------------------------------------------------

def check_events_jsonl(path: str) -> list:
    """Validate a healthmon structured event log (or a `mxdiag merge`
    output): every record a JSON object with the versioned schema tag,
    the run_id/rank/step correlation ids, non-empty kind/name, and
    non-decreasing timestamps. Schema /2 added a ``mono`` companion
    stamp (NTP-step-safe merges); it stays OPTIONAL here so /1 records
    (wall-only) keep validating — when present it must be numeric."""
    try:
        with open(path) as f:
            raw_lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    if not raw_lines:
        return [f"{path}: empty event log"]
    errors = []
    last_ts = None
    for i, ln in enumerate(raw_lines, 1):
        try:
            rec = json.loads(ln)
        except ValueError as e:
            errors.append(f"line {i}: invalid JSON: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"line {i}: record must be an object")
            continue
        schema = rec.get("schema")
        if not isinstance(schema, str) or \
                not schema.startswith(EVENTS_SCHEMA_PREFIX):
            errors.append(f"line {i}: schema must start with "
                          f"{EVENTS_SCHEMA_PREFIX!r}, got {schema!r}")
        if not _is_num(rec.get("ts")):
            errors.append(f"line {i}: needs numeric 'ts', "
                          f"got {rec.get('ts')!r}")
        else:
            if last_ts is not None and rec["ts"] < last_ts:
                errors.append(f"line {i}: ts went backwards "
                              f"({rec['ts']} < {last_ts})")
            last_ts = rec["ts"]
        if "mono" in rec and not _is_num(rec["mono"]):
            # monotone ordering is per-process, so a merged multi-process
            # file can't demand non-decreasing mono — numeric is the
            # contract here
            errors.append(f"line {i}: 'mono' must be numeric when "
                          f"present, got {rec['mono']!r}")
        if not isinstance(rec.get("run_id"), str) or not rec["run_id"]:
            errors.append(f"line {i}: missing/empty 'run_id'")
        rank = rec.get("rank")
        if not isinstance(rank, int) or isinstance(rank, bool) or rank < 0:
            errors.append(f"line {i}: 'rank' must be int >= 0, "
                          f"got {rank!r}")
        step = rec.get("step")
        if step is not None and (not isinstance(step, int)
                                 or isinstance(step, bool)):
            errors.append(f"line {i}: 'step' must be int or null, "
                          f"got {step!r}")
        for key in ("kind", "name"):
            if not isinstance(rec.get(key), str) or not rec[key]:
                errors.append(f"line {i}: missing/empty {key!r}")
        if "args" in rec and not isinstance(rec["args"], dict):
            errors.append(f"line {i}: 'args' must be an object, "
                          f"got {type(rec['args']).__name__}")
    return [f"{path}: {e}" for e in errors]


# ---------------------------------------------------------------------------
# histogram snapshots (profiler.counters.Histogram.value)
# ---------------------------------------------------------------------------

def check_histogram_snapshot(h) -> list:
    """Structural validation of one histogram snapshot dict: numeric
    count/sum, cumulative non-decreasing buckets ending in `+Inf` ==
    count, and ordered percentile estimates."""
    if not isinstance(h, dict):
        return [f"histogram snapshot must be an object, "
                f"got {type(h).__name__}"]
    errors = []
    for key in ("count", "sum"):
        if not _is_num(h.get(key)):
            errors.append(f"needs numeric {key!r}, got {h.get(key)!r}")
    buckets = h.get("buckets")
    if not isinstance(buckets, dict) or not buckets:
        errors.append("needs non-empty 'buckets'")
    else:
        prev = None
        for le, c in buckets.items():
            if not _is_num(c) or c < 0:
                errors.append(f"bucket le={le!r}: bad count {c!r}")
                continue
            if prev is not None and c < prev:
                errors.append(f"bucket le={le!r}: cumulative count "
                              f"decreased ({c} < {prev})")
            prev = c
        if "+Inf" not in buckets:
            errors.append("buckets must end with '+Inf'")
        elif _is_num(h.get("count")) and buckets["+Inf"] != h["count"]:
            errors.append(f"buckets['+Inf']={buckets['+Inf']} != "
                          f"count={h['count']}")
    pcts = [h.get(k) for k in ("p50", "p95", "p99")]
    if h.get("count"):
        if not all(_is_num(p) for p in pcts):
            errors.append(f"non-empty histogram needs numeric "
                          f"p50/p95/p99, got {pcts!r}")
        elif not (pcts[0] <= pcts[1] <= pcts[2]):
            errors.append(f"percentiles must be ordered "
                          f"p50<=p95<=p99, got {pcts!r}")
    return errors


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_PROM_METRIC = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(\{[^}]*\})?"                          # optional label set
    r"\s+(-?[0-9.eE+-]+|NaN|[+-]?Inf)\s*$")  # value
_PROM_LABELS = re.compile(
    r'^\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*,?\}$')
_PROM_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")


def check_prom(path: str) -> list:
    """Validate a Prometheus text-format file."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    errors = []
    typed = {}
    n_samples = 0
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE"):
                m = _PROM_TYPE.match(line)
                if not m:
                    errors.append(f"line {i}: malformed TYPE comment: "
                                  f"{line!r}")
                else:
                    if m.group(1) in typed:
                        errors.append(f"line {i}: duplicate TYPE for "
                                      f"{m.group(1)}")
                    typed[m.group(1)] = m.group(2)
            continue
        m = _PROM_METRIC.match(line)
        if not m:
            errors.append(f"line {i}: malformed sample line: {line!r}")
            continue
        n_samples += 1
        labels = m.group(2)
        if labels and not _PROM_LABELS.match(labels):
            errors.append(f"line {i}: malformed label set: {labels!r}")
        try:
            float(m.group(3).replace("Inf", "inf"))
        except ValueError:
            errors.append(f"line {i}: unparseable value {m.group(3)!r}")
        name = m.group(1)
        if name not in typed:
            # histogram/summary families declare the base name; their
            # samples carry the _bucket/_sum/_count suffixes
            base = None
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and \
                        typed.get(name[:-len(suffix)]) in ("histogram",
                                                           "summary"):
                    base = name[:-len(suffix)]
                    break
            if base is None:
                errors.append(f"line {i}: sample {name!r} has no "
                              f"preceding # TYPE declaration")
    if n_samples == 0:
        errors.append("no metric samples present")
    return [f"{path}: {e}" for e in errors]


# ---------------------------------------------------------------------------
# metrics newline-JSON (sampler time series)
# ---------------------------------------------------------------------------

def check_metrics_jsonl(path: str) -> list:
    """Validate a sampler metrics.jsonl: per-line schema, non-decreasing
    timestamps, and monotonic non-decreasing values for every metric of
    kind 'counter'."""
    try:
        with open(path) as f:
            raw_lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    errors = []
    if not raw_lines:
        return [f"{path}: empty metrics file"]
    last_ts = None
    last_counter_vals = {}
    seen_kinds = {}
    for i, ln in enumerate(raw_lines, 1):
        try:
            s = json.loads(ln)
        except ValueError as e:
            errors.append(f"line {i}: invalid JSON: {e}")
            continue
        if not isinstance(s, dict) or not _is_num(s.get("ts")) \
                or not isinstance(s.get("counters"), dict):
            errors.append(f"line {i}: sample needs numeric 'ts' and "
                          f"object 'counters'")
            continue
        if last_ts is not None and s["ts"] < last_ts:
            errors.append(f"line {i}: ts went backwards "
                          f"({s['ts']} < {last_ts})")
        last_ts = s["ts"]
        kinds = s.get("kinds") or {}
        seen_kinds.update(kinds)
        for name, v in s["counters"].items():
            kind = kinds.get(name)
            if kind == "histogram":
                errors += [f"line {i}: histogram {name!r}: {e}"
                           for e in check_histogram_snapshot(v)]
                n = v.get("count") if isinstance(v, dict) else None
                if not _is_num(n):
                    continue
                v = n              # observation count is the monotone series
            elif kind != "counter" or not _is_num(v):
                continue
            prev = last_counter_vals.get(name)
            if prev is not None and v < prev:
                errors.append(f"line {i}: counter {name!r} decreased "
                              f"({prev} -> {v})")
            last_counter_vals[name] = v
    errors += check_healthmon_kinds(seen_kinds)
    return [f"{path}: {e}" for e in errors]


# ---------------------------------------------------------------------------
# perfscope bench section (extra.perfscope)
# ---------------------------------------------------------------------------

def check_perfscope_extra(ps) -> list:
    """Validate an `extra.perfscope` BENCH section: per-program roofline
    records with verdicts from the known taxonomy, a peak table, and —
    when the run carried a step budget — a decomposition whose
    components sum to the measured step time within tolerance."""
    if ps is None:
        return []
    if not isinstance(ps, dict):
        return [f"must be an object, got {type(ps).__name__}"]
    errors = []
    peaks = ps.get("peaks")
    if not isinstance(peaks, dict):
        errors.append("needs a 'peaks' object")
    else:
        for key in ("peak_flops_f32", "peak_flops_bf16", "hbm_bytes_per_s"):
            v = peaks.get(key)
            if not _is_num(v) or v <= 0:
                errors.append(f"peaks[{key!r}] must be positive, got {v!r}")
    progs = ps.get("programs")
    if not isinstance(progs, list):
        errors.append("needs a 'programs' list")
    else:
        for i, p in enumerate(progs):
            if not isinstance(p, dict):
                errors.append(f"programs[{i}]: not an object")
                continue
            if not isinstance(p.get("name"), str) or not p["name"]:
                errors.append(f"programs[{i}]: missing/empty 'name'")
            if p.get("verdict") not in ROOFLINE_VERDICTS:
                errors.append(f"programs[{i}] ({p.get('name')!r}): verdict "
                              f"{p.get('verdict')!r} not in "
                              f"{ROOFLINE_VERDICTS}")
            for key in ("flops", "bytes_accessed", "ai"):
                v = p.get(key)
                if v is not None and not _is_num(v):
                    errors.append(f"programs[{i}] ({p.get('name')!r}): "
                                  f"{key!r} must be numeric or null, "
                                  f"got {v!r}")
    d = ps.get("decomposition")
    if d is None:
        return errors
    if not isinstance(d, dict):
        return errors + ["decomposition must be an object"]
    step_ms = d.get("step_ms")
    if not _is_num(step_ms) or step_ms <= 0:
        errors.append(f"decomposition.step_ms must be positive, "
                      f"got {step_ms!r}")
        return errors
    total = 0.0
    comp_ok = True
    for key in PERFSCOPE_COMPONENTS:
        v = d.get(key)
        if not _is_num(v) or v < 0:
            errors.append(f"decomposition[{key!r}] must be numeric >= 0, "
                          f"got {v!r}")
            comp_ok = False
        else:
            total += v
    if comp_ok:
        off = abs(total - step_ms) / step_ms
        if off > PERFSCOPE_SUM_TOLERANCE:
            errors.append(
                f"components sum to {total:.4g} ms but step_ms="
                f"{step_ms:.4g} ({off:.1%} apart, tolerance "
                f"{PERFSCOPE_SUM_TOLERANCE:.0%})")
    mfu = d.get("mfu")
    if mfu is not None and (not _is_num(mfu) or not 0.0 <= mfu <= 1.5):
        errors.append(f"decomposition.mfu={mfu!r} outside [0, 1.5]")
    src = d.get("collective_source")
    if src is not None and src not in COLLECTIVE_SOURCES:
        errors.append(f"decomposition.collective_source={src!r} not in "
                      f"{COLLECTIVE_SOURCES}")
    return errors


# ---------------------------------------------------------------------------
# commscope bench section (extra.commscope)
# ---------------------------------------------------------------------------

def check_commscope_extra(cs) -> list:
    """Validate an `extra.commscope` BENCH section: per-program
    collective inventories drawn from the closed op-kind taxonomy with
    non-negative bytes/counts and numeric estimates, an ICI peak table,
    and a well-formed (or null) steady-step summary."""
    if cs is None:
        return []
    if not isinstance(cs, dict):
        return [f"must be an object, got {type(cs).__name__}"]
    errors = []
    peaks = cs.get("peaks")
    if not isinstance(peaks, dict):
        errors.append("needs a 'peaks' object")
    else:
        v = peaks.get("ici_bytes_per_s")
        if not _is_num(v) or v <= 0:
            errors.append(f"peaks['ici_bytes_per_s'] must be positive, "
                          f"got {v!r}")
    progs = cs.get("programs")
    if not isinstance(progs, list):
        errors.append("needs a 'programs' list")
        progs = []
    for i, p in enumerate(progs):
        if not isinstance(p, dict):
            errors.append(f"programs[{i}]: not an object")
            continue
        where = f"programs[{i}] ({p.get('name')!r})"
        if not isinstance(p.get("name"), str) or not p["name"]:
            errors.append(f"programs[{i}]: missing/empty 'name'")
        totals = p.get("totals")
        if not isinstance(totals, dict):
            errors.append(f"{where}: missing 'totals' object")
            totals = {}
        for key in ("count", "bytes"):
            v = totals.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}: totals[{key!r}] must be an int "
                              f">= 0, got {v!r}")
        est = totals.get("est_ms")
        if not _is_num(est) or est < 0:
            errors.append(f"{where}: totals['est_ms'] must be numeric "
                          f">= 0, got {est!r}")
        colls = p.get("collectives")
        if not isinstance(colls, list):
            errors.append(f"{where}: missing 'collectives' list")
            colls = []
        kind_count = 0
        for j, c in enumerate(colls):
            if not isinstance(c, dict):
                errors.append(f"{where}: collectives[{j}] not an object")
                continue
            if c.get("kind") not in COMMSCOPE_KINDS:
                errors.append(f"{where}: collectives[{j}] kind "
                              f"{c.get('kind')!r} not in {COMMSCOPE_KINDS}")
            n = c.get("count")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(f"{where}: collectives[{j}] count must be "
                              f"an int >= 1, got {n!r}")
            else:
                kind_count += n
            b = c.get("bytes")
            if not _is_num(b) or b < 0:
                errors.append(f"{where}: collectives[{j}] bytes must be "
                              f">= 0, got {b!r}")
            e = c.get("est_ms")
            if not _is_num(e) or e < 0:
                errors.append(f"{where}: collectives[{j}] est_ms must be "
                              f"numeric >= 0, got {e!r}")
            ax = c.get("axis")
            if ax is not None and not isinstance(ax, str):
                errors.append(f"{where}: collectives[{j}] axis must be a "
                              f"string or null, got {ax!r}")
        if isinstance(totals.get("count"), int) \
                and kind_count != totals["count"] \
                and not any(not isinstance(c, dict) or
                            not isinstance(c.get("count"), int)
                            for c in colls):
            errors.append(f"{where}: per-kind counts sum to {kind_count} "
                          f"but totals.count={totals['count']}")
        r = p.get("resharding_collectives")
        if not isinstance(r, int) or isinstance(r, bool) or r < 0:
            errors.append(f"{where}: resharding_collectives must be an "
                          f"int >= 0, got {r!r}")
    step = cs.get("step")
    if step is not None:
        if not isinstance(step, dict):
            errors.append("'step' must be an object or null")
        else:
            e = step.get("est_ms")
            if e is not None and (not _is_num(e) or e < 0):
                errors.append(f"step.est_ms must be numeric >= 0 or null, "
                              f"got {e!r}")
            b = step.get("bytes")
            if b is not None and (not _is_num(b) or b < 0):
                errors.append(f"step.bytes must be >= 0 or null, got {b!r}")
    return errors


# ---------------------------------------------------------------------------
# devicescope bench section (extra.devicescope)
# ---------------------------------------------------------------------------

def check_devicescope_extra(ds) -> list:
    """Validate an `extra.devicescope` BENCH section: a window header
    (or the armed-but-no-window `window: null` shape), a busy fraction
    in [0, 1], top-K rows with non-negative measured times, measured
    collective kinds from the closed commscope taxonomy, a gap taxonomy
    whose buckets are numeric, and — when present — a reconciliation
    block whose analytic and measured sides both carry numeric
    components."""
    if ds is None:
        return []
    if not isinstance(ds, dict):
        return [f"must be an object, got {type(ds).__name__}"]
    errors = []
    win = ds.get("window")
    if win is None:
        # armed but no completed window: everything else must be empty
        if ds.get("busy_fraction") is not None:
            errors.append("window is null but busy_fraction is set")
        return errors
    if not isinstance(win, dict):
        return [f"'window' must be an object or null, "
                f"got {type(win).__name__}"]
    steps = win.get("steps")
    # 0 is legal: a window stopped before its first step mark still
    # reports honestly (its per-step numbers just use a 1-step floor)
    if not isinstance(steps, int) or isinstance(steps, bool) or steps < 0:
        errors.append(f"window.steps must be an int >= 0, got {steps!r}")
    wall = win.get("wall_ms")
    if wall is not None and (not _is_num(wall) or wall <= 0):
        errors.append(f"window.wall_ms must be positive or null, "
                      f"got {wall!r}")
    if not isinstance(win.get("path"), str) or not win["path"]:
        errors.append("window needs a non-empty 'path'")
    bf = ds.get("busy_fraction")
    if bf is not None and (not _is_num(bf) or not 0.0 <= bf <= 1.0):
        errors.append(f"busy_fraction={bf!r} outside [0, 1]")
    per = ds.get("per_step")
    if per is not None:
        if not isinstance(per, dict):
            errors.append("per_step must be an object or null")
        else:
            for key in ("device_busy_ms", "collective_ms", "idle_ms"):
                v = per.get(key)
                if not _is_num(v) or v < 0:
                    errors.append(f"per_step[{key!r}] must be numeric "
                                  f">= 0, got {v!r}")
    tops = ds.get("top_ops")
    if not isinstance(tops, list):
        errors.append("needs a 'top_ops' list")
    else:
        for i, t in enumerate(tops):
            if not isinstance(t, dict):
                errors.append(f"top_ops[{i}]: not an object")
                continue
            if not isinstance(t.get("op"), str) or not t["op"]:
                errors.append(f"top_ops[{i}]: missing/empty 'op'")
            n = t.get("count")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(f"top_ops[{i}] ({t.get('op')!r}): count "
                              f"must be an int >= 1, got {n!r}")
            v = t.get("total_ms")
            if not _is_num(v) or v < 0:
                errors.append(f"top_ops[{i}] ({t.get('op')!r}): total_ms "
                              f"must be >= 0, got {v!r}")
            verdict = t.get("verdict")
            if verdict is not None and verdict not in ROOFLINE_VERDICTS:
                errors.append(f"top_ops[{i}] ({t.get('op')!r}): verdict "
                              f"{verdict!r} not in {ROOFLINE_VERDICTS}")
    colls = ds.get("collectives")
    if colls is not None:
        if not isinstance(colls, dict):
            errors.append("collectives must be an object or null")
        else:
            for row in colls.get("by_kind") or []:
                if not isinstance(row, dict):
                    errors.append("collectives.by_kind row not an object")
                    continue
                if row.get("kind") not in COMMSCOPE_KINDS:
                    errors.append(f"collectives kind {row.get('kind')!r} "
                                  f"not in {COMMSCOPE_KINDS}")
                v = row.get("total_ms")
                if not _is_num(v) or v < 0:
                    errors.append(f"collectives[{row.get('kind')!r}] "
                                  f"total_ms must be >= 0, got {v!r}")
    gaps = ds.get("gaps")
    if gaps is not None:
        if not isinstance(gaps, dict):
            errors.append("gaps must be an object or null")
        else:
            tax = gaps.get("taxonomy")
            if not isinstance(tax, dict):
                errors.append("gaps needs a 'taxonomy' object")
            else:
                for key in DEVICESCOPE_GAP_TAXONOMY:
                    v = tax.get(key)
                    if not _is_num(v) or v < 0:
                        errors.append(f"gaps.taxonomy[{key!r}] must be "
                                      f"numeric >= 0, got {v!r}")
            split = gaps.get("input_starved_split")
            if split is not None:
                # optional: present only when the pipeline's stage walls
                # could attribute a nonzero starved bucket
                if not isinstance(split, dict):
                    errors.append("gaps.input_starved_split must be an "
                                  "object or absent")
                else:
                    for key in DEVICESCOPE_STARVED_SPLIT:
                        v = split.get(key)
                        if not _is_num(v) or v < 0:
                            errors.append(
                                f"gaps.input_starved_split[{key!r}] must "
                                f"be numeric >= 0, got {v!r}")
                    dom = split.get("dominant")
                    if dom not in DEVICESCOPE_STARVED_DOMINANTS:
                        errors.append(
                            f"gaps.input_starved_split.dominant={dom!r} "
                            f"not in {DEVICESCOPE_STARVED_DOMINANTS}")
    recon = ds.get("reconciliation")
    if recon is not None:
        if not isinstance(recon, dict):
            errors.append("reconciliation must be an object or null")
        else:
            for side in ("analytic", "measured"):
                blk = recon.get(side)
                if not isinstance(blk, dict):
                    errors.append(f"reconciliation needs a {side!r} "
                                  f"object")
                    continue
                for key in ("device_compute_ms", "collective_ms"):
                    v = blk.get(key)
                    if not _is_num(v) or v < 0:
                        errors.append(f"reconciliation.{side}[{key!r}] "
                                      f"must be >= 0, got {v!r}")
            src = (recon.get("analytic") or {}).get("collective_source")
            if src is not None and src not in COLLECTIVE_SOURCES:
                errors.append(f"reconciliation analytic "
                              f"collective_source={src!r} not in "
                              f"{COLLECTIVE_SOURCES}")
            drift = recon.get("drift")
            if drift is not None and not isinstance(drift, dict):
                errors.append("reconciliation.drift must be an object")
            elif isinstance(drift, dict):
                for k, v in drift.items():
                    if v is not None and (not _is_num(v) or v < 0):
                        errors.append(f"reconciliation.drift[{k!r}] must "
                                      f"be numeric >= 0 or null, "
                                      f"got {v!r}")
            if not isinstance(recon.get("drift_warning"), bool):
                errors.append(f"reconciliation.drift_warning must be a "
                              f"bool, got {recon.get('drift_warning')!r}")
    return errors


# ---------------------------------------------------------------------------
# memscope bench section (extra.memscope)
# ---------------------------------------------------------------------------

def check_memscope_extra(ms) -> list:
    """Validate an `extra.memscope` BENCH section: footprint records
    with non-negative bytes and the closed provenance taxonomy (an
    unavailable backend must keep the honest all-None shape), a
    bounded watermark ring whose peak dominates the latest in-use
    reading, a capacity block from the closed source taxonomy, a
    headroom verdict, and — when present — an OOM post-mortem with the
    right schema tag."""
    if ms is None:
        return []
    if not isinstance(ms, dict):
        return [f"must be an object, got {type(ms).__name__}"]
    errors = []
    progs = ms.get("programs")
    if not isinstance(progs, list):
        errors.append("needs a 'programs' list")
        progs = []
    for i, p in enumerate(progs):
        if not isinstance(p, dict):
            errors.append(f"programs[{i}]: not an object")
            continue
        name = p.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"programs[{i}]: missing/empty 'name'")
        prov = p.get("provenance")
        if prov not in MEMSCOPE_PROVENANCE:
            errors.append(f"programs[{i}] ({name!r}): provenance "
                          f"{prov!r} not in {MEMSCOPE_PROVENANCE}")
        avail = p.get("available")
        if not isinstance(avail, bool):
            errors.append(f"programs[{i}] ({name!r}): 'available' must "
                          f"be a bool, got {avail!r}")
        if avail is False:
            # armed-but-unavailable: the byte fields must stay honest
            # Nones, not invented zeros
            if prov != "unavailable":
                errors.append(f"programs[{i}] ({name!r}): unavailable "
                              f"record declares provenance {prov!r}")
            for key in MEMSCOPE_BYTE_FIELDS + ("peak_bytes",):
                if p.get(key) is not None:
                    errors.append(f"programs[{i}] ({name!r}): "
                                  f"unavailable record carries "
                                  f"{key}={p.get(key)!r}")
            continue
        for key in MEMSCOPE_BYTE_FIELDS:
            v = p.get(key)
            if not _is_num(v) or v < 0:
                errors.append(f"programs[{i}] ({name!r}): {key} must "
                              f"be numeric >= 0, got {v!r}")
        peak = p.get("peak_bytes")
        if not _is_num(peak) or peak < 0:
            errors.append(f"programs[{i}] ({name!r}): peak_bytes must "
                          f"be numeric >= 0, got {peak!r}")
        verdict = p.get("roofline")
        if verdict is not None and verdict not in ROOFLINE_VERDICTS:
            errors.append(f"programs[{i}] ({name!r}): roofline "
                          f"{verdict!r} not in {ROOFLINE_VERDICTS}")
    wm = ms.get("watermarks")
    if wm is not None:
        if not isinstance(wm, dict):
            errors.append("watermarks must be an object or null")
        else:
            n, ring, limit = (wm.get("samples"), wm.get("ring"),
                              wm.get("ring_limit"))
            for key, v in (("samples", n), ("ring", ring),
                           ("ring_limit", limit)):
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    errors.append(f"watermarks.{key} must be an int "
                                  f">= 0, got {v!r}")
            if isinstance(ring, int) and isinstance(limit, int) \
                    and ring > limit:
                errors.append(f"watermarks.ring={ring} exceeds "
                              f"ring_limit={limit} (unbounded ring)")
            if isinstance(ring, int) and isinstance(n, int) and ring > n:
                errors.append(f"watermarks.ring={ring} > samples={n} "
                              f"(phantom samples)")
            for sect in ("device", "host_rss"):
                blk = wm.get(sect)
                if blk is None:
                    continue
                if not isinstance(blk, dict):
                    errors.append(f"watermarks.{sect} must be an object "
                                  f"or null")
                    continue
                for key in ("p50", "p95", "peak", "latest"):
                    v = blk.get(key)
                    if v is not None and (not _is_num(v) or v < 0):
                        errors.append(f"watermarks.{sect}.{key} must be "
                                      f"numeric >= 0, got {v!r}")
                peak, latest = blk.get("peak"), blk.get("latest")
                if sect == "device" and _is_num(peak) \
                        and _is_num(latest) and peak < latest:
                    errors.append(f"watermarks.device peak={peak} < "
                                  f"latest in-use={latest} (a peak "
                                  f"watermark cannot undercut current "
                                  f"use)")
    cap = ms.get("capacity")
    if cap is not None:
        if not isinstance(cap, dict):
            errors.append("capacity must be an object or null")
        else:
            if cap.get("source") not in MEMSCOPE_CAPACITY_SOURCES:
                errors.append(f"capacity.source={cap.get('source')!r} "
                              f"not in {MEMSCOPE_CAPACITY_SOURCES}")
            v = cap.get("bytes")
            if v is not None and (not _is_num(v) or v <= 0):
                errors.append(f"capacity.bytes must be positive or "
                              f"null, got {v!r}")
            if cap.get("source") != "unknown" and v is None:
                errors.append(f"capacity declares source "
                              f"{cap.get('source')!r} but bytes is null")
    hr = ms.get("headroom")
    if hr is not None:
        if not isinstance(hr, dict):
            errors.append("headroom must be an object or null")
        else:
            if hr.get("verdict") not in MEMSCOPE_HEADROOM_VERDICTS:
                errors.append(f"headroom.verdict={hr.get('verdict')!r} "
                              f"not in {MEMSCOPE_HEADROOM_VERDICTS}")
            hf = hr.get("headroom_fraction")
            if hf is not None and (not _is_num(hf)
                                   or not 0.0 <= hf <= 1.0):
                errors.append(f"headroom_fraction={hf!r} outside [0, 1]")
            tgt = hr.get("target")
            if not _is_num(tgt) or not 0.0 < tgt <= 1.0:
                errors.append(f"headroom.target must be in (0, 1], "
                              f"got {tgt!r}")
            src = hr.get("in_use_source")
            if src is not None and src not in MEMSCOPE_IN_USE_SOURCES:
                errors.append(f"headroom.in_use_source={src!r} not in "
                              f"{MEMSCOPE_IN_USE_SOURCES}")
            if hr.get("verdict") != "unknown" and hf is None:
                errors.append("headroom verdict is decided but "
                              "headroom_fraction is null")
    oom = ms.get("oom")
    if oom is not None:
        if not isinstance(oom, dict):
            errors.append("oom must be an object or null")
        elif oom.get("schema") != MEMSCOPE_OOM_SCHEMA:
            errors.append(f"oom.schema={oom.get('schema')!r}, expected "
                          f"{MEMSCOPE_OOM_SCHEMA!r}")
        elif not isinstance(oom.get("error"), str) or not oom["error"]:
            errors.append("oom post-mortem needs a non-empty 'error'")
    return errors


# ---------------------------------------------------------------------------
# autotune bench section (extra.autotune)
# ---------------------------------------------------------------------------

def _check_knob_dict(d, where: str) -> list:
    """One knob config object (winner / resolved / a trial row's
    config): known fields only, each well-typed."""
    errors = []
    if not isinstance(d, dict):
        return [f"{where}: must be an object, got {type(d).__name__}"]
    unknown = sorted(set(d) - set(AUTOTUNE_KNOB_FIELDS))
    if unknown:
        errors.append(f"{where}: unknown knob field(s) {unknown} "
                      f"(update AUTOTUNE_KNOB_FIELDS if intentional)")
    for key in ("loop_chunk", "prefetch_depth"):
        v = d.get(key)
        if key in d and (not isinstance(v, int) or isinstance(v, bool)
                         or v < 0):
            errors.append(f"{where}[{key!r}] must be an int >= 0, "
                          f"got {v!r}")
    w = d.get("io_workers")
    if "io_workers" in d and (not isinstance(w, int)
                              or isinstance(w, bool) or w < 1):
        errors.append(f"{where}['io_workers'] must be an int >= 1, "
                      f"got {w!r}")
    if "remat" in d and not isinstance(d["remat"], bool):
        errors.append(f"{where}['remat'] must be a bool, "
                      f"got {d['remat']!r}")
    if d.get("remat_policy") not in AUTOTUNE_REMAT_POLICIES:
        errors.append(f"{where}['remat_policy'] {d.get('remat_policy')!r} "
                      f"not in {AUTOTUNE_REMAT_POLICIES}")
    if "pallas" in d and d["pallas"] not in AUTOTUNE_PALLAS_MODES:
        errors.append(f"{where}['pallas'] {d.get('pallas')!r} not in "
                      f"{AUTOTUNE_PALLAS_MODES}")
    b = d.get("batch")
    if b is not None and (not isinstance(b, int) or isinstance(b, bool)
                          or b < 1):
        errors.append(f"{where}['batch'] must be an int >= 1 or null, "
                      f"got {b!r}")
    m = d.get("mesh")
    if m is not None and (not isinstance(m, str) or not m):
        errors.append(f"{where}['mesh'] must be a non-empty string or "
                      f"null, got {m!r}")
    return errors


def _check_autotune_score(sc, where: str) -> list:
    """One measurement summary (score / default): busy fraction in
    [0, 1] or null, non-negative step wall, provenance from the closed
    taxonomy."""
    errors = []
    if not isinstance(sc, dict):
        return [f"{where}: must be an object, got {type(sc).__name__}"]
    bf = sc.get("busy_fraction")
    if bf is not None and (not _is_num(bf) or not 0.0 <= bf <= 1.0):
        errors.append(f"{where}.busy_fraction={bf!r} outside [0, 1]")
    for key in ("step_ms", "mfu", "value"):
        v = sc.get(key)
        if v is not None and (not _is_num(v) or v < 0):
            errors.append(f"{where}.{key} must be numeric >= 0 or "
                          f"null, got {v!r}")
    prov = sc.get("provenance")
    if prov is not None and prov not in AUTOTUNE_SCORE_SOURCES:
        errors.append(f"{where}.provenance={prov!r} not in "
                      f"{AUTOTUNE_SCORE_SOURCES}")
    return errors


def check_autotune_extra(at) -> list:
    """Validate an `extra.autotune` BENCH section: the disabled shape
    (`enabled: false`, optionally the resolved knob config), or the
    full tuning record — cache hit/miss with the hit-means-zero-trials
    invariant, trial accounting, a well-typed winner/resolved config,
    score + default measurements with closed provenance, pruning
    reasons, and a trial table whose rows carry valid statuses."""
    if at is None:
        return []
    if not isinstance(at, dict):
        return [f"must be an object, got {type(at).__name__}"]
    errors = []
    enabled = at.get("enabled")
    if not isinstance(enabled, bool):
        errors.append(f"needs a boolean 'enabled', got {enabled!r}")
        return errors
    if isinstance(at.get("resolved"), dict) or at.get("resolved") is None:
        if at.get("resolved") is not None:
            errors += _check_knob_dict(at["resolved"], "resolved")
    else:
        errors.append("'resolved' must be a knob object or null")
    if not enabled:
        return errors
    hit = at.get("cache_hit")
    if not isinstance(hit, bool):
        errors.append(f"enabled record needs boolean 'cache_hit', "
                      f"got {hit!r}")
    for key in ("trials", "trials_pruned", "trials_failed"):
        v = at.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"'{key}' must be an int >= 0, got {v!r}")
    if hit is True and at.get("trials") != 0:
        errors.append(f"cache_hit=true must report trials=0 (the "
                      f"hit-skips-search contract), got "
                      f"{at.get('trials')!r}")
    if at.get("error") is None:
        if at.get("winner") is None:
            errors.append("an enabled, error-free record needs a "
                          "'winner' config")
        else:
            errors += _check_knob_dict(at["winner"], "winner")
        if at.get("score") is not None:
            errors += _check_autotune_score(at["score"], "score")
    if at.get("default") is not None:
        errors += _check_autotune_score(at["default"], "default")
    diag = at.get("diagnosis")
    if diag not in AUTOTUNE_DIAGNOSES:
        errors.append(f"diagnosis={diag!r} not in {AUTOTUNE_DIAGNOSES}")
    pruned = at.get("pruned")
    if pruned is not None:
        if not isinstance(pruned, dict):
            errors.append("'pruned' must be an object of knob -> reason")
        else:
            for k, v in pruned.items():
                if not isinstance(v, str) or not v:
                    errors.append(f"pruned[{k!r}] needs a non-empty "
                                  f"reason string, got {v!r}")
    table = at.get("trial_table")
    if table is not None:
        if not isinstance(table, list):
            errors.append("'trial_table' must be a list")
        else:
            for i, row in enumerate(table):
                if not isinstance(row, dict):
                    errors.append(f"trial_table[{i}]: not an object")
                    continue
                if row.get("status") not in AUTOTUNE_TRIAL_STATUSES:
                    errors.append(
                        f"trial_table[{i}]: status "
                        f"{row.get('status')!r} not in "
                        f"{AUTOTUNE_TRIAL_STATUSES}")
                if row.get("status") == "failed" and not row.get("error"):
                    errors.append(f"trial_table[{i}]: failed trial "
                                  f"needs an 'error' reason")
                if isinstance(row.get("config"), dict):
                    errors += _check_knob_dict(row["config"],
                                               f"trial_table[{i}].config")
    return errors


# ---------------------------------------------------------------------------
# mxlint bench section (extra.mxlint)
# ---------------------------------------------------------------------------

def check_mxlint_extra(mx) -> list:
    """Validate an `extra.mxlint` BENCH section: the disabled shape
    (`strict: false`), or the full strict-mode audit record — the
    finding counters must be present, non-negative, and SUM to the
    `findings` total, and every recompiled program must be named."""
    if mx is None:
        return []
    if not isinstance(mx, dict):
        return [f"must be an object, got {type(mx).__name__}"]
    errors = []
    strict = mx.get("strict")
    if not isinstance(strict, bool):
        errors.append(f"needs a boolean 'strict', got {strict!r}")
        return errors
    if not strict:
        return errors
    parts = ("transfer_guard_trips", "recompiles", "donation_violations")
    for key in parts + ("findings", "allowed_syncs",
                        "guarded_dispatches"):
        v = mx.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"'{key}' must be an int >= 0, got {v!r}")
    if all(isinstance(mx.get(k), int) for k in parts + ("findings",)) \
            and mx["findings"] != sum(mx[k] for k in parts):
        errors.append(
            f"findings={mx['findings']} != "
            f"{' + '.join(parts)} = {sum(mx[k] for k in parts)}")
    rp = mx.get("recompiled_programs")
    if not isinstance(rp, list) or \
            any(not isinstance(n, str) or not n for n in rp):
        errors.append(f"'recompiled_programs' must be a list of program "
                      f"names, got {rp!r}")
    elif isinstance(mx.get("recompiles"), int) \
            and mx["recompiles"] == 0 and rp:
        errors.append(f"recompiles=0 but recompiled_programs={rp!r}")
    return errors


# ---------------------------------------------------------------------------
# io pipeline bench section (extra.io)
# ---------------------------------------------------------------------------

def check_io_extra(io) -> list:
    """Validate an `extra.io` BENCH section: the ingest-pipeline shape
    (docs/io.md). Stage walls are cumulative thread-wall milliseconds —
    they may each exceed the run wall (stages overlap), but never go
    negative, and the pipeline must declare its geometry (workers,
    depth) so a smoke comparison knows what it measured."""
    if io is None:
        return []
    if not isinstance(io, dict):
        return [f"must be an object, got {type(io).__name__}"]
    errors = []
    for key in ("workers", "depth"):
        v = io.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"'{key}' must be an int >= 1, got {v!r}")
    for key in ("batches_prefetched", "wait_ms", "read_ms",
                "decode_ms", "stage_ms", "put_ms"):
        v = io.get(key)
        if not _is_num(v) or v < 0:
            errors.append(f"'{key}' must be numeric >= 0, got {v!r}")
    for key in ("batches_skipped", "records_read", "slow_ms"):
        if key in io and (not _is_num(io[key]) or io[key] < 0):
            errors.append(f"'{key}' must be numeric >= 0, "
                          f"got {io[key]!r}")
    return errors


# ---------------------------------------------------------------------------
# servescope bench section (extra.servescope)
# ---------------------------------------------------------------------------

def _check_servescope_group(grp, where: str) -> list:
    """One attribution group (overall or one bucket): count, ordered
    e2e percentiles, per-component distributions, and quantile-cohort
    attributions whose components are non-negative, whose sum_ms equals
    the component sum, and whose sum stays within tolerance of the e2e
    quantile it attributes."""
    errors = []
    if not isinstance(grp, dict):
        return [f"{where}: must be an object, got {type(grp).__name__}"]
    n = grp.get("count")
    if not isinstance(n, int) or isinstance(n, bool) or n < 0:
        errors.append(f"{where}: count must be an int >= 0, got {n!r}")
        return errors
    if n == 0:
        return errors
    e2e = grp.get("e2e_ms")
    if not isinstance(e2e, dict):
        errors.append(f"{where}: needs an 'e2e_ms' distribution object")
    else:
        pcts = [e2e.get(k) for k in ("p50", "p95", "p99")]
        if not all(_is_num(p) for p in pcts):
            errors.append(f"{where}: e2e_ms needs numeric p50/p95/p99, "
                          f"got {pcts!r}")
        elif not (pcts[0] <= pcts[1] <= pcts[2]):
            errors.append(f"{where}: e2e percentiles must be ordered, "
                          f"got {pcts!r}")
    dist = grp.get("component_dist")
    if not isinstance(dist, dict):
        errors.append(f"{where}: needs a 'component_dist' object")
    else:
        for key in SERVESCOPE_COMPONENTS:
            if key not in dist:
                errors.append(f"{where}: component_dist missing {key!r}")
        for key in dist:
            if key not in SERVESCOPE_COMPONENTS:
                errors.append(f"{where}: component_dist key {key!r} not "
                              f"in {SERVESCOPE_COMPONENTS}")
    att = grp.get("attribution")
    if not isinstance(att, dict):
        errors.append(f"{where}: needs an 'attribution' object")
        return errors
    for q, a in att.items():
        aw = f"{where}.attribution[{q!r}]"
        if not isinstance(a, dict):
            errors.append(f"{aw}: not an object")
            continue
        qe = a.get("e2e_ms")
        if not _is_num(qe) or qe < 0:
            errors.append(f"{aw}: e2e_ms must be numeric >= 0, got {qe!r}")
            continue
        comps = a.get("components")
        if not isinstance(comps, dict):
            errors.append(f"{aw}: needs a 'components' object")
            continue
        total = 0.0
        ok = True
        for key in SERVESCOPE_COMPONENTS:
            v = comps.get(key)
            if not _is_num(v) or v < 0:
                errors.append(f"{aw}: components[{key!r}] must be "
                              f"numeric >= 0, got {v!r}")
                ok = False
            else:
                total += v
        for key in comps:
            if key not in SERVESCOPE_COMPONENTS:
                errors.append(f"{aw}: component {key!r} not in "
                              f"{SERVESCOPE_COMPONENTS}")
        s = a.get("sum_ms")
        if not _is_num(s):
            errors.append(f"{aw}: needs numeric 'sum_ms', got {s!r}")
        elif ok and abs(total - s) > max(0.05, 0.01 * max(total, s)):
            # sum_ms IS the component sum (the spans' accounting
            # identity) — disagreement means a torn producer
            errors.append(f"{aw}: components sum to {total:.4g} but "
                          f"sum_ms={s:.4g}")
        if ok and _is_num(s) and qe > 0:
            off = abs(s - qe) / qe
            if off > SERVESCOPE_SUM_TOLERANCE:
                errors.append(
                    f"{aw}: attribution sums to {s:.4g} ms but the "
                    f"e2e quantile is {qe:.4g} ms ({off:.1%} apart, "
                    f"tolerance {SERVESCOPE_SUM_TOLERANCE:.0%})")
        top = a.get("top_component")
        if top is not None and top not in SERVESCOPE_COMPONENTS:
            errors.append(f"{aw}: top_component {top!r} not in "
                          f"{SERVESCOPE_COMPONENTS}")
    return errors


def check_servescope_extra(ss) -> list:
    """Validate an `extra.servescope` BENCH section: the sampling
    header, the closed component taxonomy, the overall + per-bucket
    attribution groups (cohort sums within tolerance of their e2e
    quantiles), bucket verdicts from the roofline taxonomy, and the
    device_exec provenance."""
    if ss is None:
        return []
    if not isinstance(ss, dict):
        return [f"must be an object, got {type(ss).__name__}"]
    errors = []
    se = ss.get("sample_every")
    if se is not None and (not isinstance(se, int)
                           or isinstance(se, bool) or se < 1):
        errors.append(f"sample_every must be an int >= 1, got {se!r}")
    comps = ss.get("components")
    if comps is not None and tuple(comps) != SERVESCOPE_COMPONENTS:
        errors.append(f"components {comps!r} != the closed taxonomy "
                      f"{SERVESCOPE_COMPONENTS}")
    src = ss.get("device_exec_source")
    if src is not None and src not in SERVESCOPE_DEVICE_SOURCES:
        errors.append(f"device_exec_source {src!r} not in "
                      f"{SERVESCOPE_DEVICE_SOURCES}")
    overall = ss.get("overall")
    if overall is None:
        errors.append("needs an 'overall' attribution group")
    else:
        errors += _check_servescope_group(overall, "overall")
    pb = ss.get("per_bucket")
    if pb is not None:
        if not isinstance(pb, dict):
            errors.append("per_bucket must be an object")
        else:
            for key, grp in pb.items():
                errors += _check_servescope_group(grp,
                                                  f"per_bucket[{key!r}]")
                if not isinstance(grp, dict):
                    continue
                v = grp.get("verdict")
                if v is not None and v not in ROOFLINE_VERDICTS:
                    errors.append(f"per_bucket[{key!r}]: verdict {v!r} "
                                  f"not in {ROOFLINE_VERDICTS}")
                r = grp.get("resharding_collectives")
                if r is not None and (not isinstance(r, int)
                                      or isinstance(r, bool) or r < 0):
                    errors.append(f"per_bucket[{key!r}]: "
                                  f"resharding_collectives must be an "
                                  f"int >= 0 or null, got {r!r}")
    return errors


def check_serve_load_extra(sl) -> list:
    """Validate an `extra.serve_load` BENCH section (tools/serve_load.py
    sweeps): an ordered ramp of per-level records with positive
    concurrency/qps and ordered percentiles, and a knee whose index and
    headline numbers agree with the level it points at."""
    if sl is None:
        return []
    if not isinstance(sl, dict):
        return [f"must be an object, got {type(sl).__name__}"]
    errors = []
    levels = sl.get("levels")
    if not isinstance(levels, list) or not levels:
        return errors + ["needs a non-empty 'levels' list"]
    prev_c = 0
    for i, lv in enumerate(levels):
        where = f"levels[{i}]"
        if not isinstance(lv, dict):
            errors.append(f"{where}: not an object")
            continue
        c = lv.get("concurrency")
        if not isinstance(c, int) or isinstance(c, bool) or c < 1:
            errors.append(f"{where}: concurrency must be an int >= 1, "
                          f"got {c!r}")
        elif c <= prev_c:
            errors.append(f"{where}: ramp must be strictly ascending "
                          f"({c} after {prev_c})")
        else:
            prev_c = c
        q = lv.get("qps")
        if not _is_num(q) or q <= 0:
            errors.append(f"{where}: qps must be positive, got {q!r}")
        pcts = [lv.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
        if not all(_is_num(p) for p in pcts):
            errors.append(f"{where}: needs numeric p50/p95/p99_ms, "
                          f"got {pcts!r}")
        elif not (pcts[0] <= pcts[1] <= pcts[2]):
            errors.append(f"{where}: percentiles must be ordered, "
                          f"got {pcts!r}")
    ki = sl.get("knee_index")
    if not isinstance(ki, int) or isinstance(ki, bool) \
            or not 0 <= ki < len(levels):
        errors.append(f"knee_index {ki!r} outside the levels list")
        return errors
    knee = levels[ki] if isinstance(levels[ki], dict) else {}
    for key, lkey in (("knee_concurrency", "concurrency"),
                      ("qps_at_knee", "qps"),
                      ("p99_at_knee_ms", "p99_ms")):
        v, lv = sl.get(key), knee.get(lkey)
        if _is_num(v) and _is_num(lv) and v != lv:
            errors.append(f"{key}={v!r} disagrees with "
                          f"levels[{ki}].{lkey}={lv!r}")
    return errors


def check_fleet_extra(fl) -> list:
    """Validate an `extra.fleet` BENCH section (tools/serve_load.py
    ``--fleet N`` runs): a replica count that matches the per-replica
    rows, client-observed per-replica QPS + ordered percentiles, a
    dispatch-imbalance ratio that is mathematically possible (max/mean
    >= 1 once anything was dispatched), and router accounting that
    covers the per-replica totals."""
    if fl is None:
        return []
    if not isinstance(fl, dict):
        return [f"must be an object, got {type(fl).__name__}"]
    errors = []
    n = fl.get("replicas")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        errors.append(f"replicas must be an int >= 1, got {n!r}")
    rows = fl.get("per_replica")
    if not isinstance(rows, list) or not rows:
        return errors + ["needs a non-empty 'per_replica' list"]
    if isinstance(n, int) and not isinstance(n, bool) and n >= 1 \
            and len(rows) != n:
        errors.append(f"per_replica has {len(rows)} rows but "
                      f"replicas={n}")
    names = set()
    total_requests = 0
    for i, row in enumerate(rows):
        where = f"per_replica[{i}]"
        if not isinstance(row, dict):
            errors.append(f"{where}: not an object")
            continue
        name = row.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: needs a non-empty 'name'")
        elif name in names:
            errors.append(f"{where}: duplicate replica name {name!r}")
        else:
            names.add(name)
        reqs = row.get("requests")
        if not isinstance(reqs, int) or isinstance(reqs, bool) \
                or reqs < 0:
            errors.append(f"{where}: requests must be an int >= 0, "
                          f"got {reqs!r}")
        else:
            total_requests += reqs
        q = row.get("qps")
        if not _is_num(q) or q < 0:
            errors.append(f"{where}: qps must be >= 0, got {q!r}")
        pcts = [row.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
        if reqs:
            if not all(_is_num(p) for p in pcts):
                errors.append(f"{where}: needs numeric p50/p95/p99_ms, "
                              f"got {pcts!r}")
            elif not (pcts[0] <= pcts[1] <= pcts[2]):
                errors.append(f"{where}: percentiles must be ordered, "
                              f"got {pcts!r}")
    imb = fl.get("dispatch_imbalance")
    if total_requests:
        # max/mean over a non-degenerate dispatch is >= 1 by definition;
        # anything below 1 means the numbers were not computed from the
        # same counts
        if not _is_num(imb) or imb < 1.0:
            errors.append(f"dispatch_imbalance must be >= 1 once "
                          f"requests flowed, got {imb!r}")
    routed = fl.get("routed")
    if not _is_num(routed) or routed < 0:
        errors.append(f"routed must be >= 0, got {routed!r}")
    elif routed < total_requests:
        errors.append(f"routed={routed} < sum of per-replica "
                      f"requests={total_requests} (lost accounting)")
    for key in ("routed_errors", "no_replica_available"):
        if key in fl and (not _is_num(fl[key]) or fl[key] < 0):
            errors.append(f"{key} must be >= 0, got {fl[key]!r}")
    return errors


def check_fleetscope_extra(fs) -> list:
    """Validate an `extra.fleetscope` BENCH section (tools/serve_load.py
    runs with cross-process tracing armed): trace accounting that adds
    up (joined never exceeds the sampled denominator, a join rate in
    [0, 1] that agrees with the counts, unjoined forwards counted — not
    guessed away), ordered wire-gap percentiles (durations, so clock
    skew cannot make them meaningfully negative), and per-replica rows
    with unique names."""
    if fs is None:
        return []
    if not isinstance(fs, dict):
        return [f"must be an object, got {type(fs).__name__}"]
    errors = []
    counts = {}
    for key in ("client_minted", "sampled", "joined",
                "unjoined_forwards"):
        v = fs.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"{key} must be an int >= 0, got {v!r}")
        else:
            counts[key] = v
    if "sampled" in counts and "joined" in counts \
            and counts["joined"] > counts["sampled"]:
        errors.append(f"joined={counts['joined']} exceeds "
                      f"sampled={counts['sampled']}")
    rate = fs.get("join_rate")
    if not _is_num(rate) or not (0.0 <= rate <= 1.0):
        errors.append(f"join_rate must be in [0, 1], got {rate!r}")
    elif "sampled" in counts and "joined" in counts and counts["sampled"]:
        want = counts["joined"] / counts["sampled"]
        if abs(rate - want) > 1e-6:
            errors.append(f"join_rate={rate} disagrees with "
                          f"joined/sampled={want:.6f}")
    gap = fs.get("wire_gap_ms")
    if gap is not None:
        if not isinstance(gap, dict):
            errors.append("wire_gap_ms must be an object of percentiles")
        else:
            pcts = [gap.get(k) for k in ("p50", "p95", "p99")]
            if not all(_is_num(p) for p in pcts):
                errors.append(f"wire_gap_ms needs numeric p50/p95/p99, "
                              f"got {pcts!r}")
            elif not (pcts[0] <= pcts[1] <= pcts[2]):
                errors.append(f"wire_gap_ms percentiles must be ordered, "
                              f"got {pcts!r}")
            elif pcts[0] < -1.0:
                # the gap is a DIFFERENCE OF DURATIONS (router-observed
                # forward minus replica-observed total), so no clock
                # offset enters it; anything past scheduling noise
                # negative means the join mixed up its sides
                errors.append(f"wire_gap_ms.p50={pcts[0]} < -1 ms — a "
                              f"duration difference cannot be this "
                              f"negative")
    rows = fs.get("per_replica")
    if rows is not None:
        if not isinstance(rows, list):
            return errors + ["per_replica must be a list"]
        names = set()
        for i, row in enumerate(rows):
            where = f"per_replica[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where}: not an object")
                continue
            name = row.get("name")
            if not isinstance(name, str) or not name:
                errors.append(f"{where}: needs a non-empty 'name'")
            elif name in names:
                errors.append(f"{where}: duplicate replica name {name!r}")
            else:
                names.add(name)
            t = row.get("traces")
            if not isinstance(t, int) or isinstance(t, bool) or t < 0:
                errors.append(f"{where}: traces must be an int >= 0, "
                              f"got {t!r}")
            for key in ("e2e_p99_ms", "wire_gap_p50_ms"):
                v = row.get(key)
                if v is not None and not _is_num(v):
                    errors.append(f"{where}: {key} must be numeric or "
                                  f"absent, got {v!r}")
    spread = fs.get("replica_spread")
    if spread is not None and (not _is_num(spread) or spread < 1.0):
        # max/median of per-replica p99 — >= 1 by construction once
        # any replica has traces
        errors.append(f"replica_spread must be >= 1 when present, "
                      f"got {spread!r}")
    return errors


def check_sharding_extra(sh) -> list:
    """Validate an `extra.sharding` BENCH section (bench.py BENCH_MESH
    runs): a positive mesh shape, a mode from the closed taxonomy, and
    spec counts that add up to the param total."""
    if sh is None:
        return []
    if not isinstance(sh, dict):
        return [f"must be an object, got {type(sh).__name__}"]
    errors = []
    mesh = sh.get("mesh")
    if not isinstance(mesh, dict) or not mesh:
        errors.append(f"needs a non-empty 'mesh' axis->size object, "
                      f"got {mesh!r}")
    else:
        for ax, size in mesh.items():
            if not isinstance(size, int) or size < 1:
                errors.append(f"mesh[{ax!r}] must be a positive int, "
                              f"got {size!r}")
    if sh.get("mode") not in SHARDING_MODES:
        errors.append(f"mode {sh.get('mode')!r} not in {SHARDING_MODES}")
    if not isinstance(sh.get("fsdp"), bool):
        errors.append(f"fsdp must be a bool, got {sh.get('fsdp')!r}")
    counts = {}
    for key in ("params_total", "params_model_sharded",
                "params_data_sharded", "params_replicated"):
        v = sh.get(key)
        if not isinstance(v, int) or v < 0:
            errors.append(f"{key} must be an int >= 0, got {v!r}")
        else:
            counts[key] = v
    if len(counts) == 4:
        parts = (counts["params_model_sharded"]
                 + counts["params_data_sharded"]
                 + counts["params_replicated"])
        if parts != counts["params_total"]:
            errors.append(f"spec counts sum to {parts} but params_total="
                          f"{counts['params_total']}")
    for key in ("param_bytes_per_device", "state_bytes_per_device"):
        v = sh.get(key)
        if v is not None and (not _is_num(v) or v < 0):
            errors.append(f"{key} must be numeric >= 0 or absent, "
                          f"got {v!r}")
    return errors


def check_embedding_extra(em) -> list:
    """Validate an `extra.embedding` BENCH section (BENCH_MODEL=recsys
    runs; emitted by mxtpu.embedding.bench_extra): the table census
    (logical vs per-device bytes — sharded means per-device <=
    logical), the dedup accounting (rate in [0, 1], rows touched never
    above ids seen), and the closed out-of-range-id policy."""
    if em is None:
        return []
    if not isinstance(em, dict):
        return [f"must be an object, got {type(em).__name__}"]
    errors = []
    for key in ("tables", "table_bytes_logical", "table_bytes_per_device",
                "rows_total", "ids_per_step", "rows_touched_per_step",
                "oor_ids", "lookups"):
        v = em.get(key)
        if not _is_num(v) or v < 0:
            errors.append(f"{key} must be numeric >= 0, got {v!r}")
    logical = em.get("table_bytes_logical")
    per_dev = em.get("table_bytes_per_device")
    if _is_num(logical) and _is_num(per_dev) and per_dev > logical:
        errors.append(f"table_bytes_per_device={per_dev} exceeds the "
                      f"replicated footprint table_bytes_logical={logical}")
    rate = em.get("dedup_rate")
    if not _is_num(rate) or not (0.0 <= rate <= 1.0):
        errors.append(f"dedup_rate must be in [0, 1], got {rate!r}")
    ids = em.get("ids_per_step")
    rows = em.get("rows_touched_per_step")
    if _is_num(ids) and _is_num(rows) and rows > ids:
        errors.append(f"rows_touched_per_step={rows} exceeds "
                      f"ids_per_step={ids}")
    if em.get("oor_policy") not in ("clip", "error"):
        errors.append(f"oor_policy {em.get('oor_policy')!r} not in "
                      f"('clip', 'error')")
    return errors


# ---------------------------------------------------------------------------
# bench result JSON (BENCH_*.json with serving stats)
# ---------------------------------------------------------------------------

def check_resilience_extra(rx) -> list:
    """Validate a BENCH `extra.resilience` block (resilience.bench_extra):
    recovery accounting must be numeric and non-negative, the save/copy
    cost blocks must carry ordered percentiles, and a recovery count
    implies a rollback/resume trail (a recovered run is USABLE but its
    cost must be visible — perf_regress notes it, never hides it)."""
    if rx is None:
        return []
    if not isinstance(rx, dict):
        return ["must be an object"]
    errors = []
    for key in ("checkpoints_saved", "recoveries_total", "rollbacks",
                "steps_lost_last", "steps_lost_total"):
        v = rx.get(key)
        if not _is_num(v):
            errors.append(f"needs numeric {key!r}, got {v!r}")
        elif v < 0:
            errors.append(f"{key}={v} negative")
    lcs = rx.get("last_checkpoint_step")
    if lcs is not None and not _is_num(lcs):
        errors.append(f"last_checkpoint_step must be numeric or null, "
                      f"got {lcs!r}")
    for blk in ("save", "copy"):
        b = rx.get(blk)
        if b is None:
            continue
        if not isinstance(b, dict):
            errors.append(f"{blk} block must be an object or null")
            continue
        if not _is_num(b.get("count")) or b["count"] < 0:
            errors.append(f"{blk}.count must be numeric >= 0, "
                          f"got {b.get('count')!r}")
        p50, p95 = b.get("p50_ms"), b.get("p95_ms")
        for k, v in (("p50_ms", p50), ("p95_ms", p95)):
            if v is not None and not _is_num(v):
                errors.append(f"{blk}.{k} must be numeric or null")
        if _is_num(p50) and _is_num(p95) and p50 > p95:
            errors.append(f"{blk} percentiles out of order "
                          f"(p50={p50} > p95={p95})")
    if _is_num(rx.get("every")) and rx["every"] < 0:
        errors.append(f"every={rx['every']} negative")
    if _is_num(rx.get("keep")) and rx["keep"] < 1:
        errors.append(f"keep={rx['keep']} < 1")
    if _is_num(rx.get("recoveries_total")) and rx["recoveries_total"] > 0:
        trail = sum(rx.get(k, 0) or 0
                    for k in ("rollbacks", "resumes", "rank_departures")
                    if _is_num(rx.get(k)))
        if trail == 0:
            errors.append(
                f"recoveries_total={rx['recoveries_total']} with no "
                f"rollback/resume/departure trail — a recovery must say "
                f"what it was")
    return errors


def check_bench_json(path: str) -> list:
    """Validate a bench.py result line/file. Core keys always; when the
    run was the serving benchmark, its `extra.serving` section must carry
    well-formed latency histograms and request accounting."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable/invalid JSON: {e}"]
    errors = []
    if not isinstance(doc, dict):
        return [f"{path}: bench result must be a JSON object"]
    if not isinstance(doc.get("metric"), str) or not doc["metric"]:
        errors.append("missing/empty 'metric'")
    if not _is_num(doc.get("value")):
        errors.append(f"needs numeric 'value', got {doc.get('value')!r}")
    extra = doc.get("extra") or {}
    # training benches must carry MFU (ROADMAP item 1: regressions visible
    # per-PR). Serving benches and error results are exempt.
    if (isinstance(extra, dict) and extra
            and "serving" not in extra and "error" not in doc):
        mfu = extra.get("mfu")
        if not _is_num(mfu):
            errors.append(f"training bench extra needs numeric 'mfu', "
                          f"got {mfu!r}")
        elif not (0.0 <= mfu <= 1.5):
            errors.append(f"extra.mfu={mfu} outside [0, 1.5] — wrong "
                          f"peak-FLOPs or flops-per-sample accounting")
    errors += [f"extra.perfscope: {e}"
               for e in check_perfscope_extra(
                   (doc.get("extra") or {}).get("perfscope"))]
    errors += [f"extra.commscope: {e}"
               for e in check_commscope_extra(
                   (doc.get("extra") or {}).get("commscope"))]
    errors += [f"extra.devicescope: {e}"
               for e in check_devicescope_extra(
                   (doc.get("extra") or {}).get("devicescope"))]
    errors += [f"extra.memscope: {e}"
               for e in check_memscope_extra(
                   (doc.get("extra") or {}).get("memscope"))]
    errors += [f"extra.sharding: {e}"
               for e in check_sharding_extra(
                   (doc.get("extra") or {}).get("sharding"))]
    errors += [f"extra.servescope: {e}"
               for e in check_servescope_extra(
                   (doc.get("extra") or {}).get("servescope"))]
    errors += [f"extra.serve_load: {e}"
               for e in check_serve_load_extra(
                   (doc.get("extra") or {}).get("serve_load"))]
    errors += [f"extra.fleet: {e}"
               for e in check_fleet_extra(
                   (doc.get("extra") or {}).get("fleet"))]
    errors += [f"extra.resilience: {e}"
               for e in check_resilience_extra(
                   (doc.get("extra") or {}).get("resilience"))]
    errors += [f"extra.autotune: {e}"
               for e in check_autotune_extra(
                   (doc.get("extra") or {}).get("autotune"))]
    errors += [f"extra.mxlint: {e}"
               for e in check_mxlint_extra(
                   (doc.get("extra") or {}).get("mxlint"))]
    errors += [f"extra.io: {e}"
               for e in check_io_extra(
                   (doc.get("extra") or {}).get("io"))]
    errors += [f"extra.embedding: {e}"
               for e in check_embedding_extra(
                   (doc.get("extra") or {}).get("embedding"))]
    errors += [f"extra.fleetscope: {e}"
               for e in check_fleetscope_extra(
                   (doc.get("extra") or {}).get("fleetscope"))]
    serving = (doc.get("extra") or {}).get("serving")
    if serving is not None:
        if not isinstance(serving, dict):
            return [f"{path}: extra.serving must be an object"]
        for key in ("requests", "responses", "batches", "batch_fill",
                    "p50_ms", "p95_ms", "p99_ms", "qps"):
            if not _is_num(serving.get(key)):
                errors.append(f"extra.serving needs numeric {key!r}, "
                              f"got {serving.get(key)!r}")
        for key in ("rejected_queue_full", "rejected_deadline",
                    "rejected_deadline_post_batch", "rejected_invalid"):
            if key in serving and not _is_num(serving[key]):
                errors.append(f"extra.serving[{key!r}] must be numeric")
        hist = serving.get("latency_ms")
        if hist is None:
            errors.append("extra.serving needs a 'latency_ms' histogram")
        else:
            errors += [f"extra.serving.latency_ms: {e}"
                       for e in check_histogram_snapshot(hist)]
            if isinstance(hist, dict) and _is_num(serving.get("responses")) \
                    and _is_num(hist.get("count")) \
                    and hist["count"] < serving["responses"]:
                errors.append(
                    f"latency_ms.count={hist['count']} < "
                    f"responses={serving['responses']} (lost observations)")
        if _is_num(serving.get("batch_fill")) and serving["batch_fill"] < 1.0:
            errors.append(f"batch_fill={serving['batch_fill']} < 1.0 "
                          f"(more batches than requests?)")
        ordered = [serving.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
        if all(_is_num(p) for p in ordered) and \
                not (ordered[0] <= ordered[1] <= ordered[2]):
            errors.append(f"serving percentiles must be ordered, "
                          f"got {ordered!r}")
    return [f"{path}: {e}" for e in errors]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def check_file(path: str) -> list:
    """Validate one file, auto-detecting its kind: `.prom`/`.txt` →
    Prometheus, `.jsonl` → metrics time series, JSON object with a
    flight `schema` → flight dump, a bench result (has `metric` +
    `value`) → bench JSON, anything else → Chrome trace."""
    low = path.lower()
    if low.endswith((".prom", ".txt")):
        return check_prom(path)
    if low.endswith(".jsonl"):
        # events vs metrics series: event records are self-describing
        # (every line carries the schema tag), so sniff the first line
        try:
            with open(path) as f:
                first = f.readline()
        except OSError as e:
            return [f"{path}: unreadable: {e}"]
        if f'"{EVENTS_SCHEMA_PREFIX}' in first:
            return check_events_jsonl(path)
        return check_metrics_jsonl(path)
    try:
        with open(path) as f:
            head = f.read(4096)
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    if f'"{FLIGHT_SCHEMA_PREFIX}' in head:
        return check_flight(path)
    if '"metric"' in head and '"value"' in head:
        # bench result detection must parse the WHOLE document — a
        # serving/diag bench json easily exceeds the 4 KB sniff window
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = None
        if isinstance(doc, dict) and "metric" in doc and "value" in doc:
            return check_bench_json(path)
    return check_trace(path)


def main(argv) -> int:
    if not argv:
        print(__doc__.strip().splitlines()[0])
        print("usage: python tools/trace_check.py FILE [...]")
        return 2
    rc = 0
    for path in argv:
        errors = check_file(path)
        if errors:
            rc = 1
            for e in errors:
                print(e, file=sys.stderr)
        else:
            print(f"{path}: OK")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
