#!/usr/bin/env python
"""Measured healthmon overhead on the 50-step CPU lenet bench.

Two sequential `bench.py` processes proved useless for a <5%% assertion:
on a loaded CI box the machine drifts more between runs than the effect
being measured (observed: the second run's BASELINE slower than the
first run's healthmon-on run). This harness removes drift with a PAIRED
design: ONE process, one compiled FusedTrainStep, alternating 5-step
chunks with healthmon's hook off / on — 50 measured steps per side,
same executable, same memory layout, adjacent in time — and the verdict
is the MEDIAN of per-pair on/off ratios (a paired median is robust to
the ±10%% per-chunk scheduler noise a shared CI box shows; a sum would
let one preempted chunk decide the verdict). "Off" is the real off
state (the module predicate `healthmon._HM` is None, the exact guard
every hook site uses); "on" is healthmon at default settings (event log
+ watchdogs + EWMA timeline, single-process exchange).

Prints a JSON verdict and exits 0 iff overhead < the budget (default
5%%, HEALTH_OVERHEAD_BUDGET_PCT to widen on known-noisy machines).
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STEPS_PER_SIDE = int(os.environ.get("HEALTH_OVERHEAD_STEPS", "50"))
CHUNK = 5
BUDGET_PCT = float(os.environ.get("HEALTH_OVERHEAD_BUDGET_PCT", "5"))


def main() -> int:
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu import healthmon as hm
    from incubator_mxnet_tpu.models import get_model
    from incubator_mxnet_tpu.parallel import FusedTrainStep

    out_dir = os.environ.get("MXTPU_HM_OUT", "/tmp/mxtpu_health_overhead")
    os.makedirs(out_dir, exist_ok=True)
    np.random.seed(0)
    mx.random.seed(0)
    batch = 64
    net = get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    x = nd.array(np.random.rand(batch, 1, 28, 28).astype(np.float32))
    y = nd.array(np.random.randint(0, 10, batch))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
    step = FusedTrainStep(net, L, opt)
    float(step(x, y))                      # compile
    float(step(x, y))                      # warmup

    mon = hm.enable(hm_dir=out_dir, stall_timeout_s=1200)

    def run_chunk(with_hm: bool) -> float:
        # toggle THE module predicate — the exact off-state every hook
        # site (trainer/kvstore/bench) checks
        hm._HM = mon if with_hm else None
        t0 = time.perf_counter()
        for _ in range(CHUNK):
            loss = step(x, y)
            if hm._HM is not None:
                hm._HM.step_end()
        float(loss)                        # host fetch = chunk barrier
        return time.perf_counter() - t0

    pairs = []
    for _ in range(STEPS_PER_SIDE // CHUNK):
        off = run_chunk(False)
        on = run_chunk(True)
        pairs.append((off, on))
    hm._HM = mon
    hm.disable()

    import statistics
    ratios = sorted(on / off for off, on in pairs)
    med_ratio = statistics.median(ratios)
    overhead_pct = 100.0 * (med_ratio - 1.0)
    off_med = statistics.median(off for off, _ in pairs)
    on_med = statistics.median(on for _, on in pairs)
    verdict = {
        "metric": "healthmon_overhead_pct",
        "steps_per_side": STEPS_PER_SIDE,
        "off_step_ms": round(off_med / CHUNK * 1e3, 3),
        "on_step_ms": round(on_med / CHUNK * 1e3, 3),
        "pair_ratios": [round(r, 4) for r in ratios],
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": BUDGET_PCT,
        "events_file": mon.events.path,
    }
    print(json.dumps(verdict), flush=True)
    return 0 if overhead_pct < BUDGET_PCT else 3


if __name__ == "__main__":
    sys.exit(main())
