#!/usr/bin/env python
"""mxlint — the framework-invariant static analyzer (docs/mxlint.md).

Runs the mxtpu.mxlint rule suite (stdlib ast, no deps) over the repo:

    python tools/mxlint.py --check            # gate: exit 1 on findings
    python tools/mxlint.py path/to/file.py    # lint specific paths
    python tools/mxlint.py --list-rules       # rule table with hints
    python tools/mxlint.py --check --json     # machine-readable findings

Default lint set: the ``incubator_mxnet_tpu/`` package, ``tools/`` and
``bench.py`` (tests/, examples/ and docs/ are excluded — fixtures carry
deliberate violations). Per-rule path scopes live on the rules
themselves (e.g. ``raw-env-read`` judges only the package: BENCH_* is
the driver layer's own documented spelling).

Suppression: ``# mxlint: disable=<rule> -- <reason>`` (the reason is
required; a reasonless directive suppresses nothing and is itself a
finding). ``auto_guard.sh`` / ``auto_sweep.sh`` run ``--check`` before
spending any tunnel time, and a tier-1 test runs it over the tree.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_mxlint():
    """Import the rule suite WITHOUT importing the full framework
    package: load the mxlint subpackage by path under its canonical
    name. The static lint needs no jax/backend, must stay seconds-fast
    in the auto_guard gate, and must not trigger the package's
    MXTPU_*-armed import side effects (healthmon watchdogs, strict
    auditor) just to parse source. Reuse an already-imported package's
    subpackage (pytest) so there is never a second module object."""
    existing = sys.modules.get("incubator_mxnet_tpu.mxlint")
    if existing is not None:
        return existing
    import importlib.util
    pkg_dir = os.path.join(_REPO, "incubator_mxnet_tpu", "mxlint")
    spec = importlib.util.spec_from_file_location(
        "incubator_mxnet_tpu.mxlint",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    # the subpackage's relative imports need a parent in sys.modules
    # while it loads; when the real package was never imported, install
    # a stand-in for the duration and REMOVE it afterwards so a later
    # real `import incubator_mxnet_tpu` in this process still runs the
    # genuine package init
    fake_parent = "incubator_mxnet_tpu" not in sys.modules
    if fake_parent:
        import types
        parent = types.ModuleType("incubator_mxnet_tpu")
        parent.__path__ = [os.path.dirname(pkg_dir)]
        sys.modules["incubator_mxnet_tpu"] = parent
    sys.modules["incubator_mxnet_tpu.mxlint"] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop("incubator_mxnet_tpu.mxlint", None)
        raise
    finally:
        if fake_parent:
            sys.modules.pop("incubator_mxnet_tpu", None)
    return mod


def default_paths() -> list:
    return [os.path.join(_REPO, "incubator_mxnet_tpu"),
            os.path.join(_REPO, "tools"),
            os.path.join(_REPO, "bench.py")]


def run_lint(paths=None, rules=None, root=None):
    """Lint entry point shared with mxdiag/tests. Returns (findings,
    root). An EXPLICIT path that does not exist is an error — a typo'd
    gate invocation must fail, not report a clean empty lint set."""
    mxlint = _load_mxlint()
    if paths is None:
        # the optional default entries may be absent in a stripped tree
        paths = [p for p in default_paths() if os.path.exists(p)]
    else:
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"mxlint: no such path(s): {missing} — nothing would be "
                f"linted, refusing to report a clean tree")
    root = root or _REPO
    # the static rule set only — the runtime auditor is armed by
    # MXTPU_STRICT, not by the CLI
    rules = rules if rules is not None else mxlint.rules.default_rules()
    return mxlint.engine.lint_paths(paths, rules, root=root), root


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package, "
                         "tools/ and bench.py)")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: print findings, exit 1 if any")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only these rule ids (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    mxlint = _load_mxlint()
    if args.list_rules:
        for r in mxlint.rules.default_rules():
            print(f"{r.id}")
            print(f"    fix: {r.hint}")
        print(f"{mxlint.engine.SUPPRESSION_RULE_ID}")
        print("    fix: append ' -- <reason>' to the mxlint directive")
        return 0

    rules = None
    if args.rule:
        rules = [mxlint.rules.rule_by_id(rid) for rid in args.rule]
    try:
        findings, root = run_lint(args.paths or None, rules=rules)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "count": len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.render(root=root))
        n = len(findings)
        print(f"mxlint: {n} finding{'s' if n != 1 else ''}"
              + ("" if n else " — tree is clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
