#!/bin/bash
# Tier-1 sharding smoke: the CPU-mesh matrix on 4 FAKE host devices
# (XLA_FLAGS=--xla_force_host_platform_device_count=4 — no TPU, no
# tunnel). Four 50-step lenet bench runs:
#   baseline  (no mesh)          -> the reference loss
#   dp4       BENCH_MESH=dp4     -> pure data parallel
#   dp2mp2    BENCH_MESH=dp2mp2  -> 2x2 (dp, mp): Dense kernels on mp
#   fsdp4     BENCH_MESH=fsdp4   -> zero-style param+state sharding
# and from the BENCH jsons assert that
#   * every sharded run's final loss matches the unsharded run within
#     tolerance (dp/mp layouts are bit-identical on XLA:CPU; fsdp is
#     ~1 ulp/step from collective reduction order),
#   * the sharding.* counter family and extra.sharding are present and
#     describe the requested mesh (trace_check-schema-validated),
#   * dp2mp2 actually put params on the mp axis,
#   * FSDP per-device param+state bytes < the replicated runs' (the
#     memory reduction is the point of the mode).
set -u
cd "$(dirname "$0")/.." || exit 1

OUTDIR=${1:-/tmp/mxtpu_shard_smoke}
mkdir -p "$OUTDIR"
LOG="$OUTDIR/shard_smoke.log"
: > "$LOG"

run_one() {
  name=$1; mesh=$2
  echo "shard_smoke: $name (BENCH_MESH='${mesh}')"
  env XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
    BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 BENCH_DTYPE=float32 \
    BENCH_MESH="$mesh" BENCH_K1_CONTROL=0 BENCH_PERFSCOPE_PROBE=2 \
    BENCH_TRACE_FILE="$OUTDIR/trace_$name.json" \
    timeout -k 10 900 python bench.py > "$OUTDIR/bench_$name.json" 2>> "$LOG"
  rc=$?
  if [ "$rc" != "0" ]; then
    echo "shard_smoke: bench ($name) failed rc=$rc"; tail -30 "$LOG"
    exit 1
  fi
}

run_one baseline ""
run_one dp4 dp4
run_one dp2mp2 dp2mp2
run_one fsdp4 fsdp4

python - "$OUTDIR" <<'EOF' || exit 1
import json, os, sys
outdir = sys.argv[1]
docs = {n: json.load(open(os.path.join(outdir, f"bench_{n}.json")))
        for n in ("baseline", "dp4", "dp2mp2", "fsdp4")}
for n, d in docs.items():
    assert not d.get("error"), f"{n}: bench reported error: {d.get('error')}"
ref = docs["baseline"]["extra"]["final_loss"]
for n in ("dp4", "dp2mp2", "fsdp4"):
    d = docs[n]
    loss = d["extra"]["final_loss"]
    # bench rounds final_loss to 4 decimals; dp/mp are bit-identical and
    # fsdp drifts ~1 ulp/step, so 5e-3 is generous while still catching
    # any real divergence (wrong batch split, double-applied grads, ...)
    assert abs(loss - ref) < 5e-3, \
        f"{n}: final_loss {loss} vs unsharded {ref} — sharded math diverged"
    sh = d["extra"].get("sharding")
    assert sh, f"{n}: no extra.sharding in BENCH json"
    c = d["extra"]["counters"]
    for fam in ("sharding/sharding.resolves",
                "sharding/sharding.mesh_devices",
                "sharding/sharding.params_total",
                "sharding/sharding.param_bytes_per_device"):
        assert fam in c, f"{n}: counter {fam} missing from BENCH json"
    assert c["sharding/sharding.mesh_devices"] == 4, \
        f"{n}: mesh_devices={c['sharding/sharding.mesh_devices']}"

assert docs["dp4"]["extra"]["sharding"]["mesh"] == {"dp": 4}
assert docs["dp2mp2"]["extra"]["sharding"]["mesh"] == {"dp": 2, "mp": 2}
n_mp = docs["dp2mp2"]["extra"]["sharding"]["params_model_sharded"]
assert n_mp > 0, "dp2mp2: no params landed on the mp axis"

fsdp = docs["fsdp4"]["extra"]["sharding"]
repl = docs["dp4"]["extra"]["sharding"]
assert fsdp["fsdp"] and fsdp["params_data_sharded"] > 0, fsdp
for key in ("param_bytes_per_device", "state_bytes_per_device"):
    assert fsdp[key] < repl[key], \
        (f"fsdp {key}={fsdp[key]} not below replicated {repl[key]} — "
         f"FSDP saved no memory")
red = repl["param_bytes_per_device"] / fsdp["param_bytes_per_device"]
print(f"shard_smoke: OK (loss ref={ref}, dp4/dp2mp2/fsdp4 within tol; "
      f"{n_mp} params on mp; fsdp per-device param bytes "
      f"{fsdp['param_bytes_per_device']} vs {repl['param_bytes_per_device']}"
      f" = {red:.2f}x reduction)")
EOF

# schema-check every artifact (sharding counter family + extra.sharding)
python tools/trace_check.py "$OUTDIR"/bench_*.json || exit 1
echo "shard_smoke: CPU-mesh matrix validates"
