#!/usr/bin/env python
"""im2rec: image folder -> .lst / .rec / .idx (parity: the reference's
tools/im2rec.py data-prep CLI).

Labels come from the immediate subdirectory of `root` (sorted name order,
like the reference's folder walk); pass an existing .lst to pack a curated
split instead. Images are re-encoded to JPEG at --quality (and optionally
--resize shortest side) so training-time decode is uniform — the
reference's offline-preprocessing recipe that keeps the input pipeline
chip-bound instead of decode-bound.

Usage:
  python tools/im2rec.py PREFIX ROOT [--list] [--resize N] [--quality Q]
                                     [--exts .jpg,.jpeg,.png]

  --list       only generate PREFIX.lst (index \t label \t relpath)
  otherwise    read/auto-generate PREFIX.lst and write PREFIX.rec + .idx
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_list(root, exts):
    """[(index, label, relpath)] — labels by sorted subdirectory name."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: float(i) for i, c in enumerate(classes)}
    entries = []
    i = 0
    for c in classes:
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if os.path.splitext(f)[1].lower() in exts:
                entries.append((i, label_of[c], os.path.join(c, f)))
                i += 1
    if not entries:
        raise SystemExit(f"no images with extensions {sorted(exts)} under "
                         f"{root!r}")
    return entries


def write_list(path, entries):
    with open(path, "w") as f:
        for idx, label, rel in entries:
            f.write(f"{idx}\t{label:g}\t{rel}\n")


def read_list(path):
    out = []
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            out.append((int(parts[0]), float(parts[1]), parts[-1]))
    return out


def pack(prefix, root, entries, resize, quality):
    import numpy as np
    from PIL import Image

    from incubator_mxnet_tpu import recordio

    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "w")
    n = 0
    for idx, label, rel in entries:
        img = Image.open(os.path.join(root, rel)).convert("RGB")
        if resize:
            w, h = img.size
            s = resize / min(w, h)
            img = img.resize((max(1, round(w * s)), max(1, round(h * s))),
                             Image.BILINEAR)
        payload = recordio.pack_img(
            recordio.IRHeader(0, label, idx, 0),
            np.asarray(img, np.uint8), quality=quality)
        writer.write_idx(idx, payload)
        n += 1
        if n % 1000 == 0:
            print(f"packed {n} images", file=sys.stderr)
    writer.close()
    print(f"wrote {n} records -> {prefix}.rec / {prefix}.idx")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (PREFIX.lst/.rec/.idx)")
    p.add_argument("root", help="image folder (class subdirectories)")
    p.add_argument("--list", action="store_true",
                   help="only generate PREFIX.lst")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shortest side to N pixels (0 = keep)")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--exts", default=".jpg,.jpeg,.png",
                   help="comma-separated image extensions")
    a = p.parse_args(argv)
    exts = {e if e.startswith(".") else "." + e
            for e in a.exts.lower().split(",")}

    lst = a.prefix + ".lst"
    if a.list or not os.path.exists(lst):
        entries = make_list(a.root, exts)
        write_list(lst, entries)
        print(f"wrote {len(entries)} entries -> {lst}")
        if a.list:
            return
    entries = read_list(lst)
    pack(a.prefix, a.root, entries, a.resize, a.quality)


if __name__ == "__main__":
    main()
