#!/usr/bin/env python
"""im2rec: image folder -> .lst / .rec / .idx (parity: the reference's
tools/im2rec.py data-prep CLI).

Labels come from the immediate subdirectory of `root` (sorted name order,
like the reference's folder walk); pass an existing .lst to pack a curated
split instead. Images are re-encoded to JPEG at --quality (and optionally
--resize shortest side) so training-time decode is uniform — the
reference's offline-preprocessing recipe that keeps the input pipeline
chip-bound instead of decode-bound.

Usage:
  python tools/im2rec.py PREFIX ROOT [--list] [--resize N] [--quality Q]
                                     [--exts .jpg,.jpeg,.png]

  --list       only generate PREFIX.lst (index \t label \t relpath)
  otherwise    read/auto-generate PREFIX.lst and write PREFIX.rec + .idx
"""
import argparse
import os
import sys

# host-only tool: never initialize an accelerator backend (the framework
# import would otherwise register the TPU platform for pure CPU work)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_list(root, exts):
    """[(index, label, relpath)] — labels by sorted subdirectory name."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: float(i) for i, c in enumerate(classes)}
    entries = []
    i = 0
    for c in classes:
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if os.path.splitext(f)[1].lower() in exts:
                entries.append((i, label_of[c], os.path.join(c, f)))
                i += 1
    if not entries:
        raise SystemExit(f"no images with extensions {sorted(exts)} under "
                         f"{root!r}")
    return entries


def write_list(path, entries):
    with open(path, "w") as f:
        for idx, label, rel in entries:
            f.write(f"{idx}\t{label:g}\t{rel}\n")


def read_list(path):
    """idx \t label... \t relpath — multi-label rows keep the full label
    vector (the reference's detection/multi-task .lst format)."""
    out = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            if not line.strip():
                continue
            parts = line.rstrip("\n").split("\t")
            try:
                if len(parts) < 3:
                    raise ValueError("need idx, label(s), path")
                labels = [float(v) for v in parts[1:-1]]
                label = labels[0] if len(labels) == 1 else labels
                out.append((int(parts[0]), label, parts[-1]))
            except (ValueError, IndexError):
                raise SystemExit(f"{path}:{ln}: malformed .lst line "
                                 f"{line.rstrip()!r}")
    return out


def pack(prefix, root, entries, resize, quality):
    import numpy as np
    from PIL import Image

    from incubator_mxnet_tpu import recordio

    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "w")
    n = skipped = 0
    for idx, label, rel in entries:
        try:
            img = Image.open(os.path.join(root, rel)).convert("RGB")
            if resize:
                w, h = img.size
                s = resize / min(w, h)
                img = img.resize((max(1, round(w * s)),
                                  max(1, round(h * s))), Image.BILINEAR)
            # recordio.pack handles list labels (float32 vector + flag)
            payload = recordio.pack_img(
                recordio.IRHeader(0, label, idx, 0),
                np.asarray(img, np.uint8), quality=quality)
        except Exception as e:  # noqa: BLE001 — one bad image must not
            skipped += 1        # abort an hours-long pack (reference logs
            print(f"skipping {rel}: {type(e).__name__}: {e}",
                  file=sys.stderr)      # and continues the same way)
            continue
        writer.write_idx(idx, payload)
        n += 1
        if n % 1000 == 0:
            print(f"packed {n} images", file=sys.stderr)
    writer.close()
    msg = f"wrote {n} records -> {prefix}.rec / {prefix}.idx"
    if skipped:
        msg += f" ({skipped} unreadable images skipped)"
    print(msg)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (PREFIX.lst/.rec/.idx)")
    p.add_argument("root", help="image folder (class subdirectories)")
    p.add_argument("--list", action="store_true",
                   help="only generate PREFIX.lst")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shortest side to N pixels (0 = keep)")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--exts", default=".jpg,.jpeg,.png",
                   help="comma-separated image extensions")
    a = p.parse_args(argv)
    exts = {e if e.startswith(".") else "." + e
            for e in a.exts.lower().split(",")}

    lst = a.prefix + ".lst"
    if a.list or not os.path.exists(lst):
        entries = make_list(a.root, exts)
        write_list(lst, entries)
        print(f"wrote {len(entries)} entries -> {lst}")
        if a.list:
            return
    entries = read_list(lst)
    pack(a.prefix, a.root, entries, a.resize, a.quality)


if __name__ == "__main__":
    main()
