#!/bin/bash
# Resilience smoke: the chaos harness end to end on CPU, plus the
# telemetry/gate plumbing around it. Proves, without a TPU:
#
#  1. chaos_cluster.py — all four injected faults (poison-NaN batch,
#     torn checkpoint, frozen source -> stall -> restart, mid-step rank
#     SIGKILL + elastic re-join) recover with loss DECREASING and the
#     recovery visible on counters + flight + events (the harness
#     asserts the three-surface contract itself), the merged timeline
#     trace_check-valid, and `mxdiag.py recover` rendering it clean;
#  2. a BENCH_RESILIENCE=1 training bench emits a trace_check-valid
#     extra.resilience block (async checkpoint cadence + save-cost
#     percentiles) with ZERO recovery counters on a healthy run;
#  3. perf_regress accepts that artifact self-vs-self (a resilient run
#     is a usable perf number, not an env failure).
#
# Wired into tools/auto_guard.sh / tools/auto_sweep.sh like every other
# subsystem smoke. Exit 0 = all good.
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
OUT=${MXTPU_SMOKE_OUT:-/tmp/mxtpu_resilience_smoke}
rm -rf "$OUT"; mkdir -p "$OUT"
fail() { echo "resilience_smoke: FAIL: $*" >&2; exit 1; }

echo "== resilience_smoke: chaos harness (nan + torn + freeze + kill) =="
MXTPU_CHAOS_OUT="$OUT/chaos" timeout 580 python tools/chaos_cluster.py \
  > "$OUT/chaos.log" 2>&1
rc=$?
tail -n 12 "$OUT/chaos.log"
[ $rc -eq 0 ] || fail "chaos_cluster rc=$rc (log: $OUT/chaos.log)"
grep -q "CHAOS_OK" "$OUT/chaos.log" || fail "no CHAOS_OK verdict"

echo "== resilience_smoke: BENCH_RESILIENCE training bench =="
BENCH_JSON="$OUT/BENCH_resilience.json"
BENCH_MODEL=lenet BENCH_BATCH=32 BENCH_STEPS=50 BENCH_DTYPE=float32 \
  BENCH_K1_CONTROL=0 BENCH_RESILIENCE=1 BENCH_RESILIENCE_EVERY=10 \
  BENCH_RESILIENCE_DIR="$OUT/bench_ckpt" \
  timeout -k 10 900 python bench.py > "$BENCH_JSON" 2> "$OUT/bench.log" \
  || { tail -n 30 "$OUT/bench.log"; fail "bench run failed"; }

python - "$BENCH_JSON" <<'EOF' || exit 1
import json, sys
sys.path.insert(0, "tools")
import trace_check as tc
path = sys.argv[1]
errs = tc.check_bench_json(path)
assert not errs, f"BENCH json invalid: {errs[:5]}"
doc = json.load(open(path))
rx = (doc.get("extra") or {}).get("resilience")
assert rx, "BENCH json carries no extra.resilience"
assert not tc.check_resilience_extra(rx), tc.check_resilience_extra(rx)
assert rx["checkpoints_saved"] >= 1, f"no checkpoints saved: {rx}"
assert rx["recoveries_total"] == 0, \
    f"healthy bench run recorded recoveries: {rx}"
assert rx["save"] and rx["save"]["count"] >= 1, f"no save costs: {rx}"
print(f"resilience extra OK: {rx['checkpoints_saved']} ckpt(s), "
      f"save p50 {rx['save']['p50_ms']:.0f} ms, 0 recoveries")
EOF
[ $? -eq 0 ] || fail "extra.resilience validation"

echo "== resilience_smoke: perf_regress accepts the resilient artifact =="
python tools/perf_regress.py "$BENCH_JSON" "$BENCH_JSON" \
  || fail "perf_regress rejected a resilient run self-vs-self"

echo "resilience_smoke: OK"
