#!/bin/bash
# Tier-1 commscope smoke: 50 lenet train steps ON CPU through bench.py
# under BENCH_MESH=fsdp4 on 4 FAKE host devices (no TPU, no tunnel) with
# collective extraction armed, then assert from the BENCH json that
#   * extra.commscope is present with the steady train program captured,
#   * the collective inventory is NONZERO (fsdp must all-gather params
#     and reduce the grads — an empty inventory means extraction broke),
#   * every op kind is from the closed taxonomy and the payload bytes /
#     estimates are well-formed,
#   * the resharding detector found NOTHING (the bench net is correctly
#     annotated; a count here is a real finding or a detector bug),
#   * the step budget's collective component carries provenance
#     "estimated" (the kvstore counter is blind to in-program GSPMD
#     collectives — reporting a measured zero is the bug this layer
#     fixes),
#   * the artifact trace_check-validates (commscope.* counter family +
#     extra.commscope schema) and `mxdiag.py comms` renders it.
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_comms_smoke_bench.json}
LOG=/tmp/mxtpu_comms_smoke.log

echo "comms_smoke: 50 lenet steps on a 4-fake-device fsdp mesh"
env XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
  BENCH_MODEL=lenet BENCH_BATCH=64 BENCH_STEPS=50 BENCH_DTYPE=float32 \
  BENCH_MESH=fsdp4 BENCH_K1_CONTROL=0 BENCH_PERFSCOPE_PROBE=2 \
  BENCH_TRACE_FILE=/tmp/mxtpu_comms_smoke_trace.json \
  timeout -k 10 900 python bench.py > "$OUT" 2> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "comms_smoke: bench.py failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"bench reported error: {doc['error']}")
cs = (doc.get("extra") or {}).get("commscope")
assert isinstance(cs, dict), "no extra.commscope in BENCH json"
progs = {p["name"]: p for p in cs.get("programs") or []}
train = [p for n, p in progs.items() if n.startswith("fused_step")]
assert train, f"no fused_step program captured (got {sorted(progs)})"
rec = train[-1]
t = rec["totals"]
assert t["count"] > 0 and t["bytes"] > 0, \
    f"fsdp4 inventory empty: {t} (extraction broke)"
kinds = {c["kind"] for c in rec["collectives"]}
allowed = {"all-reduce", "all-gather", "reduce-scatter", "all-to-all",
           "collective-permute", "other"}
assert kinds <= allowed, f"kinds outside taxonomy: {kinds - allowed}"
assert "all-gather" in kinds, \
    f"fsdp4 shows no all-gather (kinds={sorted(kinds)}) — the mode's " \
    f"param gather is missing from the inventory"
assert rec["resharding_collectives"] == 0, \
    f"resharding detector fired on the correctly-annotated bench net: " \
    f"{rec['resharding']}"
step = cs.get("step")
assert isinstance(step, dict) and step.get("bytes", 0) > 0, \
    f"no steady-step collective summary: {step}"
d = ((doc.get("extra") or {}).get("perfscope") or {}).get("decomposition")
assert isinstance(d, dict), "no perfscope decomposition to carry provenance"
assert d.get("collective_source") == "estimated", \
    f"sharded-mode collective provenance is {d.get('collective_source')!r}," \
    f" expected 'estimated' (measured-zero is the mis-attribution bug)"
c = (doc.get("extra") or {}).get("counters") or {}
for name in ("commscope/commscope.programs_analyzed",
             "commscope/commscope.collectives",
             "commscope/commscope.payload_bytes",
             "commscope/commscope.step_collective_bytes"):
    assert name in c, f"counter {name} missing from BENCH json"
assert c.get("commscope/commscope.resharding_collectives", 0) == 0, \
    "resharding counter nonzero on a clean layout"
print(f"comms_smoke: inventory OK ({t['count']} collectives, "
      f"{t['bytes']} B, est {t['est_ms']:.4f} ms/step, "
      f"kinds={sorted(kinds)}, provenance=estimated)")
EOF

# schema-check the BENCH json (commscope counter family + extra schema)
python tools/trace_check.py "$OUT" || exit 1

# the comms renderer must read a real artifact end-to-end
python tools/mxdiag.py comms "$OUT" > /tmp/mxtpu_comms_smoke_render.txt \
  || { echo "comms_smoke: mxdiag.py comms failed on the artifact"; exit 1; }
grep -q "all-gather" /tmp/mxtpu_comms_smoke_render.txt \
  || { echo "comms_smoke: comms table missing the all-gather row"; exit 1; }

echo "comms_smoke: collective observability validates"
