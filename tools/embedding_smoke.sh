#!/bin/bash
# Tier-1 embedding-subsystem smoke: 50 recsys (DLRM) steps ON CPU over
# a 4-fake-device model-axis mesh (BENCH_MESH=mp4) through the sharded
# one-jit executor with the row-sparse AdaGrad path, then assert the
# subsystem's whole contract from the one BENCH json:
#   learning   — final_loss < first_loss (the label rides the table
#                rows, so a flat loss means the lookup/update path is
#                broken, not the model);
#   sharding   — extra.embedding.table_bytes_per_device strictly below
#                table_bytes_logical (the vocab axis really split) and
#                extra.sharding shows model-sharded params on an mp
#                mesh in auto mode;
#   dedup      — a real dedup rate in (0, 1] with rows_touched <= ids
#                (zipf ids make it ~0.9+; 0 means the unique/inverse
#                path fell out of the program);
#   comms      — commscope attributes at least one steady-train
#                collective to the mp axis (the sharded lookup's
#                all-reduce / all-to-all spelling), and the resharding
#                detector stays QUIET (0 flagged) — the annotated
#                layout matches the computation;
#   schema     — the artifact validates under tools/trace_check.py
#                (extra.embedding + counter families included).
# No TPU, no tunnel — safe anywhere, cheap enough for CI.
set -u
cd "$(dirname "$0")/.." || exit 1

OUT=${1:-/tmp/mxtpu_embedding_smoke.json}
LOG=/tmp/mxtpu_embedding_smoke.log
: > "$LOG"

echo "embedding_smoke: 50-step recsys run on a CPU mp4 mesh"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
  BENCH_MODEL=recsys BENCH_MESH=mp4 BENCH_BATCH=256 BENCH_STEPS=50 \
  BENCH_DTYPE=float32 BENCH_PREFLIGHT=0 BENCH_TRACE=0 \
  timeout -k 10 900 python bench.py > "$OUT" 2>> "$LOG"
rc=$?
if [ "$rc" != "0" ]; then
  echo "embedding_smoke: recsys bench failed rc=$rc"; tail -30 "$LOG"
  exit 1
fi

python - "$OUT" <<'EOF' || exit 1
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("error"):
    sys.exit(f"recsys bench reported error: {doc['error']}")
ex = doc.get("extra") or {}

# learning: the synthetic labels are a function of the table rows used,
# so the loss only moves if lookup, backward, and row update all work
fl, ll = ex.get("first_loss"), ex.get("final_loss")
assert isinstance(fl, (int, float)) and isinstance(ll, (int, float)), \
    f"first/final loss missing: {fl} {ll}"
assert ll < fl, f"loss did not decrease: first {fl} -> final {ll}"

# embedding census: the table really lives split on the vocab axis
em = ex.get("embedding")
assert isinstance(em, dict), "no extra.embedding section"
assert em["tables"] > 0, em
assert 0 < em["table_bytes_per_device"] < em["table_bytes_logical"], \
    (f"table not sharded: {em['table_bytes_per_device']} B/device vs "
     f"{em['table_bytes_logical']} B replicated")
assert 0.0 < em["dedup_rate"] <= 1.0, f"dedup rate: {em['dedup_rate']}"
assert em["rows_touched_per_step"] <= em["ids_per_step"], em

# sharding summary: auto mode on an mp mesh, model-sharded params > 0
sh = ex.get("sharding")
assert isinstance(sh, dict), "no extra.sharding section"
assert sh.get("mesh", {}).get("mp") == 4, sh
assert sh.get("params_model_sharded", 0) > 0, sh

# commscope: the sharded lookup's collective is attributed to the mp
# axis somewhere in the captured programs, and the resharding detector
# is quiet — the annotated layout matches what XLA compiled
cs = ex.get("commscope")
assert isinstance(cs, dict) and cs.get("programs"), "no commscope data"
mp_colls = [c for p in cs["programs"] for c in (p.get("collectives") or [])
            if c.get("axis") == "mp"]
assert mp_colls, "no collective attributed to the mp axis"
flagged = sum(p.get("resharding_collectives", 0) for p in cs["programs"])
assert flagged == 0, f"resharding detector flagged {flagged} collective(s)"

print(f"embedding_smoke: OK (loss {fl} -> {ll}; "
      f"{em['table_bytes_per_device']} B/device of "
      f"{em['table_bytes_logical']} B tables; dedup "
      f"{em['dedup_rate']:.3f}; {len(mp_colls)} mp-axis collective "
      f"kind(s); resharding 0)")
EOF

# schema-check the artifact (extra.embedding + counter families)
python tools/trace_check.py "$OUT" || exit 1

echo "embedding_smoke: OK"
